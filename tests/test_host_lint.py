"""Host concurrency lint (ISSUE 13): injected counterexamples through
the production rule path, the clean production sweep, the runtime
witness layer, and regression tests for the real races the lint
surfaced in the pre-existing code.

Convention (since R1): every counterexample is a deliberately broken
input fed through the EXACT production engine (``run_host_lint`` — the
function ``mpi-knn lint --host`` calls), never a hand-driven rule
object. The production sweep itself is asserted clean — zero non-waived
findings, waivers enumerated with rationale, lock-acquisition graph
acyclic FROM THE REPORT — via the real CLI.
"""

from __future__ import annotations

import json
import textwrap
import threading

import pytest

from mpi_knn_tpu.analysis.host import (
    ClassGuard,
    GuardMap,
    HostTarget,
    run_host_lint,
)
from mpi_knn_tpu.analysis.host.witness import (
    InstrumentedLock,
    WitnessLog,
    instrument,
)


def _target(tmp_path, name: str, src: str) -> HostTarget:
    p = tmp_path / f"{name}.py"
    p.write_text(textwrap.dedent(src))
    return HostTarget(name, ((name, str(p)),))


def _findings(report, rule=None):
    return [
        f for f in report.findings if rule is None or f.rule == rule
    ]


# ---------------------------------------------------------------------------
# injected counterexamples (>= 8, each through run_host_lint)


def test_unguarded_write_fires(tmp_path):
    """H1: a guarded attribute written with no lock held."""
    t = _target(tmp_path, "cx1", """
        import threading

        class W:
            def __init__(self):
                self._lock = threading.Lock()
                self.count = 0

            def _run(self):
                self.count += 1  # no lock

            def start(self):
                threading.Thread(target=self._run).start()

            def read(self):
                with self._lock:
                    return self.count
    """)
    g = GuardMap()
    g.classes["cx1.W"] = ClassGuard(guarded={"count": "_lock"})
    rep = run_host_lint([t], guards=g)
    f = _findings(rep, "H1-lock-discipline")
    assert len(f) == 1 and f[0].where == "cx1.W._run"
    assert "with no lock held" in f[0].message
    assert not rep.ok


def test_wrong_lock_guard_fires(tmp_path):
    """H1: the access holds A lock — just not the declared one."""
    t = _target(tmp_path, "cx2", """
        import threading

        class W:
            def __init__(self):
                self._lock = threading.Lock()
                self._other = threading.Lock()
                self.items = []

            def _run(self):
                with self._other:
                    self.items.append(1)

            def start(self):
                threading.Thread(target=self._run).start()
    """)
    g = GuardMap()
    g.classes["cx2.W"] = ClassGuard(guarded={"items": "_lock"})
    rep = run_host_lint([t], guards=g)
    f = _findings(rep, "H1-lock-discipline")
    assert len(f) == 1 and "WRONG lock" in f[0].message
    assert "cx2.W._other" in f[0].message


def test_lock_order_cycle_fires(tmp_path):
    """H2: A->B lexically, B->A through the call graph — a cycle, found
    statically and named in the report's lock graph."""
    t = _target(tmp_path, "cx3", """
        import threading

        _a = threading.Lock()
        _b = threading.Lock()

        def forward():
            with _a:
                with _b:
                    pass

        def backward():
            with _b:
                helper()

        def helper():
            with _a:
                pass
    """)
    rep = run_host_lint([t], guards=GuardMap())
    f = _findings(rep, "H2-lock-order")
    assert len(f) == 1 and "cycle" in f[0].message
    assert rep.lock_graph.cycles == [["cx3:_a", "cx3:_b"]]
    assert not rep.lock_graph.acyclic and not rep.ok


def test_self_deadlock_fires(tmp_path):
    """H2: re-acquiring a held non-reentrant lock through a call."""
    t = _target(tmp_path, "cx3b", """
        import threading

        _m = threading.Lock()

        def outer():
            with _m:
                inner()

        def inner():
            with _m:
                pass
    """)
    rep = run_host_lint([t], guards=GuardMap())
    f = _findings(rep, "H2-lock-order")
    assert len(f) == 1 and "self-deadlock" in f[0].message


def test_confinement_breach_from_http_handler_fires(tmp_path):
    """H3: a pump-confined attribute reachable from a declared
    HTTP-handler root."""
    t = _target(tmp_path, "cx4", """
        import threading

        class Pump:
            def __init__(self):
                self.inflight = []

            def _run(self):
                self.inflight.append(1)

            def start(self):
                threading.Thread(target=self._run).start()

        class Handler:
            def do_GET(self):
                return peek(self)

        def peek(handler):
            return len(PUMP.inflight)
    """)
    g = GuardMap()
    g.classes["cx4.Pump"] = ClassGuard(confined={"inflight": "pump"})
    g.roots["pump"] = ["cx4.Pump._run"]
    g.roots["http-handler"] = ["cx4.Handler.do_GET"]
    g.name_types["cx4"] = {"PUMP": "cx4.Pump"}
    rep = run_host_lint([t], guards=g)
    f = _findings(rep, "H3-confinement")
    assert len(f) == 1 and f[0].where == "cx4.peek"
    assert "http-handler" in f[0].message


def test_bare_open_w_in_cache_writer_fires(tmp_path):
    """H4: a bare truncating write in a threaded cache-entry writer —
    and the temp+os.replace idiom in the same module passes."""
    t = _target(tmp_path, "cx5", """
        import os

        def store_entry(path, blob):
            with open(path, "wb") as f:   # torn-read window
                f.write(blob)

        def store_entry_atomic(path, blob):
            tmp = path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(blob)
            os.replace(tmp, path)
    """)
    rep = run_host_lint([t], guards=GuardMap())
    f = _findings(rep, "H4-atomic-publish")
    assert len(f) == 1 and f[0].where == "cx5.store_entry"
    # the atomic variant is untouched; a waiver silences the bare one
    g = GuardMap()
    g.h4_waivers["cx5.store_entry"] = "test-only artifact, single writer"
    rep2 = run_host_lint([t], guards=g)
    assert not _findings(rep2, "H4-atomic-publish")
    assert any("store_entry" in str(w["where"]) for w in rep2.waivers)


def test_undeclared_shared_attribute_fires(tmp_path):
    """H1 enforcement teeth: an attribute in NO guard map, mutated
    outside __init__, touched from two thread roots."""
    t = _target(tmp_path, "cx6", """
        import threading

        class S:
            def __init__(self):
                self.state = {}

            def _writer(self):
                self.state["x"] = 1

            def _reader(self):
                return dict(self.state)

            def start(self):
                threading.Thread(target=self._writer).start()
                threading.Thread(target=self._reader).start()
    """)
    rep = run_host_lint([t], guards=GuardMap())
    f = _findings(rep, "H1-lock-discipline")
    assert len(f) == 1 and "undeclared shared attribute" in f[0].message
    assert "cx6.S.state" == f[0].attr


def test_waiver_honored_and_counted(tmp_path):
    """The same undeclared-shared module goes green under an explicit
    waiver — and the waiver is enumerated in the report (it cannot
    accrete silently)."""
    t = _target(tmp_path, "cx6", """
        import threading

        class S:
            def __init__(self):
                self.state = {}

            def _writer(self):
                self.state["x"] = 1

            def _reader(self):
                return dict(self.state)

            def start(self):
                threading.Thread(target=self._writer).start()
                threading.Thread(target=self._reader).start()
    """)
    g = GuardMap()
    g.classes["cx6.S"] = ClassGuard(
        waivers={"state": "benign last-write-wins cache (test)"}
    )
    rep = run_host_lint([t], guards=g)
    assert rep.ok and not rep.findings
    assert rep.waivers == [{
        "where": "cx6.S.state",
        "rationale": "benign last-write-wins cache (test)",
    }]
    assert rep.to_json()["summary"]["waivers"] == 1


def test_clean_module_green(tmp_path):
    """A correctly-locked module produces zero findings and the right
    lock-order edge."""
    t = _target(tmp_path, "cx7", """
        import threading

        class W:
            def __init__(self):
                self._lock = threading.Lock()
                self._inner = threading.Lock()
                self.count = 0

            def _run(self):
                with self._lock:
                    self.count += 1
                    with self._inner:
                        pass

            def start(self):
                threading.Thread(target=self._run).start()

            def read(self):
                with self._lock:
                    return self.count
    """)
    g = GuardMap()
    g.classes["cx7.W"] = ClassGuard(guarded={"count": "_lock"})
    rep = run_host_lint([t], guards=g)
    assert rep.ok and not rep.findings
    assert ("cx7.W._lock", "cx7.W._inner") in set(rep.lock_graph.edges)
    assert rep.lock_graph.acyclic


def test_undeclared_global_fires_and_module_guard_passes(tmp_path):
    """H1 on module globals: an unguarded lazy singleton fires; the
    declared module lock silences it when actually held."""
    src = """
        import threading

        _lock = threading.Lock()
        _cached = None

        def get(make):
            global _cached
            {body}

        def worker(make):
            get(make)

        def start(make):
            threading.Thread(target=worker, args=(make,)).start()
            threading.Thread(target=worker, args=(make,)).start()
    """
    bad = _target(tmp_path, "cx8", src.format(body="""
            if _cached is None:
                _cached = make()
            return _cached"""))
    rep = run_host_lint([bad], guards=GuardMap())
    f = _findings(rep, "H1-lock-discipline")
    assert f and "module global" in f[0].message
    good = _target(tmp_path, "cx8b", src.format(body="""
            with _lock:
                if _cached is None:
                    _cached = make()
                return _cached"""))
    g = GuardMap()
    g.module_guards["cx8b"] = {"_cached": "cx8b:_lock"}
    rep2 = run_host_lint([good], guards=g)
    assert rep2.ok


def test_stale_guard_map_is_a_problem(tmp_path):
    """A declared root naming a function that no longer exists makes
    the report NOT ok — config rot cannot silently hollow the lint."""
    t = _target(tmp_path, "cx9", """
        def f():
            return 1
    """)
    g = GuardMap()
    g.roots["pump"] = ["cx9.gone"]
    rep = run_host_lint([t], guards=g)
    assert not rep.ok and rep.problems


# ---------------------------------------------------------------------------
# the production sweep, via the production CLI


def test_production_sweep_clean_via_cli(tmp_path, capsys):
    """``mpi-knn lint --host``: exit 0 over all seven threaded-module
    targets, zero non-waived findings, waivers enumerated with
    rationale, and the lock-acquisition graph asserted acyclic FROM THE
    REPORT (the ISSUE 13 acceptance)."""
    from mpi_knn_tpu.analysis.cli import main as lint_main

    rc = lint_main(["--host", "-q", "--out", str(tmp_path)])
    assert rc == 0
    doc = json.loads((tmp_path / "host_report.json").read_text())
    assert doc["ok"] is True
    assert doc["summary"]["findings"] == 0
    assert doc["summary"]["problems"] == 0
    # all seven targets, each individually ok (serve.mutate joined in
    # ISSUE 14: the background compactor thread)
    names = {t["name"] for t in doc["targets"]}
    assert names == {
        "frontend", "serve.engine", "serve.mutate", "serve.aotcache",
        "obs.metrics", "obs.spans", "resilience.worker",
    }
    assert all(t["ok"] for t in doc["targets"])
    # the lock graph is present, non-trivial, and acyclic
    lg = doc["lock_graph"]
    assert lg["acyclic"] is True and lg["cycles"] == []
    assert "serve.engine.ServeSession._stats_lock" in lg["nodes"]
    assert ["frontend.server.Frontend._lock",
            "serve.engine.ServeSession._stats_lock"] in lg["edges"]
    # waivers are enumerated, each with a non-empty rationale
    assert doc["summary"]["waivers"] == len(doc["waivers"]) > 0
    assert all(w["rationale"].strip() for w in doc["waivers"])
    # the thread roots the rules reasoned about are the serving stack's
    assert "dispatch-pump" in doc["roots"]
    assert "http-handler" in doc["roots"]
    assert "warm-pool" in doc["roots"]


def test_host_rule_filter_and_usage_error(tmp_path):
    from mpi_knn_tpu.analysis.cli import main as lint_main

    assert lint_main(["--host", "-q", "--out", str(tmp_path),
                      "--rule", "H2-lock-order"]) == 0
    doc = json.loads((tmp_path / "host_report.json").read_text())
    assert list(doc["rules"]) == ["H2-lock-order"]
    assert lint_main(["--host", "--rule", "H9-nope"]) == 2


def test_production_sweep_would_catch_the_fixed_races(tmp_path):
    """The regression pin for the real pre-existing bugs this PR fixed:
    re-introduce the old unguarded patterns in a fixture mirroring the
    production classes and guard map — warm_state published without its
    lock, a histogram snapshot reading counts barewise, the /healthz
    path reading session window stats raw — and the production rules
    fire on every one."""
    t = _target(tmp_path, "old", """
        import threading

        class Session:
            def __init__(self):
                self._warm_lock = threading.Lock()
                self._stats_lock = threading.Lock()
                self.warm_state = {}
                self.latencies = []

            def warm(self):
                self.warm_state = {"total": 1}  # old bug: no lock

            def retire(self):
                with self._stats_lock:
                    self.latencies.append(1.0)

        class Front:
            def __init__(self, session):
                self._lock = threading.Lock()
                self.session = session

            def _run(self):
                self.session.retire()

            def start(self):
                threading.Thread(target=self._run).start()

            def stats(self):
                ses = self.session
                with self._lock:
                    return len(ses.latencies), dict(ses.warm_state)
    """)
    g = GuardMap()
    g.classes["old.Session"] = ClassGuard(guarded={
        "warm_state": "_warm_lock", "latencies": "_stats_lock",
    })
    g.attr_types["old.Front.session"] = "old.Session"
    g.roots["http-handler"] = ["old.Front.stats"]
    g.roots["warm-pool"] = ["old.Session.warm"]
    rep = run_host_lint([t], guards=g)
    assert {f.attr for f in rep.findings} == {
        "old.Session.latencies", "old.Session.warm_state",
    }
    assert len(rep.findings) == 3  # warm write + two raw stats reads
    assert {f.where for f in rep.findings} == {
        "old.Session.warm", "old.Front.stats",
    }


# ---------------------------------------------------------------------------
# runtime witnesses (armed in tests only)


def test_witness_observes_lock_order_inversion():
    """The dynamic twin of the H2 counterexample: both orders of a lock
    pair observed at runtime → a reported inversion. (The two orders
    run sequentially — observing an inversion must not require actually
    deadlocking.)"""
    log = WitnessLog()
    a = InstrumentedLock("A", log)
    b = InstrumentedLock("B", log)

    def forward():
        with a:
            with b:
                pass

    def backward():
        with b:
            with a:
                pass

    t1 = threading.Thread(target=forward)
    t1.start(); t1.join()
    t2 = threading.Thread(target=backward)
    t2.start(); t2.join()
    assert log.inversions() == {("A", "B")}
    assert {("A", "B"), ("B", "A")} <= log.ordered_pairs()


def test_witness_observes_guard_violation():
    """The dynamic twin of the H1 counterexample: an access recorded
    without its declared lock held is a violation; the guarded access
    is not."""
    log = WitnessLog()
    lock = InstrumentedLock("W._lock", log)
    state = {"count": 0}

    def guarded():
        with lock:
            state["count"] += 1
            log.note_access("W.count", "write")

    def unguarded():
        state["count"] += 1
        log.note_access("W.count", "write")

    t = threading.Thread(target=guarded)
    t.start(); t.join()
    t = threading.Thread(target=unguarded)
    t.start(); t.join()
    bad = log.guard_violations({"W.count": "W._lock"})
    assert len(bad) == 1 and bad[0].held == ()


def test_witness_instruments_production_registry():
    """instrument() swaps a REAL MetricsRegistry's lock for the
    recording wrapper: driving the production get-or-create path shows
    the acquisition, and no ordering is ever observed against a metric's
    own lock (the registry releases before the metric snapshots — the
    disjoint-critical-section design the lock graph also shows)."""
    from mpi_knn_tpu.obs.metrics import MetricsRegistry

    reg = MetricsRegistry()
    log = WitnessLog()
    with instrument(reg, log, "_lock", prefix="obs."):
        c = reg.counter("witness_total", help="x")
        c.inc()
        reg.snapshot()
    names = [ev.lock for ev in log.acquires]
    assert names.count("obs.MetricsRegistry._lock") >= 2  # create + snapshot
    assert log.inversions() == set()


# ---------------------------------------------------------------------------
# regression tests for the real races the lint surfaced


def test_histogram_snapshot_consistent_under_concurrent_observe():
    """Pre-fix, Histogram.snapshot read counts/sum/count outside the
    lock: a scrape racing observe() could export counts summing to
    count±1. Post-fix every snapshot is internally consistent."""
    from mpi_knn_tpu.obs.metrics import Histogram

    h = Histogram("lat", buckets=(0.1, 1.0, 10.0))
    stop = threading.Event()

    def hammer():
        i = 0
        while not stop.is_set():
            h.observe((i % 40) * 0.3)
            i += 1

    t = threading.Thread(target=hammer)
    t.start()
    try:
        for _ in range(300):
            snap = h.snapshot()
            assert sum(snap["counts"]) == snap["count"]
    finally:
        stop.set()
        t.join()
    assert h.count == sum(h.snapshot()["counts"])


def test_counter_snapshot_takes_lock():
    from mpi_knn_tpu.obs.metrics import Counter, Gauge

    c = Counter("c_total")
    c.inc(2.5)
    assert c.snapshot()["value"] == 2.5 and c.value == 2.5
    g = Gauge("g")
    g.set(4.0)
    g.add(-1.0)
    assert g.snapshot()["value"] == 3.0


def test_get_recorder_returns_one_instance_across_threads(
    tmp_path, monkeypatch
):
    """Pre-fix, two threads could lazily construct two FlightRecorders
    onto one TKNN_FLIGHT_RECORD path (interleaved ring generations).
    Post-fix the module lock makes the singleton real."""
    from mpi_knn_tpu.obs import spans

    monkeypatch.setenv(spans.RECORDER_ENV, str(tmp_path / "fl.jsonl"))
    monkeypatch.setattr(spans, "_env_recorder", None)
    monkeypatch.setattr(spans, "_recorder", None)
    got = []
    barrier = threading.Barrier(8)

    def grab():
        barrier.wait()
        got.append(spans.get_recorder())

    threads = [threading.Thread(target=grab) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len({id(r) for r in got}) == 1 and got[0] is not None


def test_warm_and_stats_snapshots_are_consistent_copies(rng):
    """The ServeSession cross-thread readers added for the /healthz
    path: warm_snapshot/stats_snapshot return consistent COPIES (a
    reader mutating one cannot corrupt session state), and the posture
    matches the session's own window."""
    import numpy as np

    from mpi_knn_tpu.config import KNNConfig
    from mpi_knn_tpu.serve import ServeSession, build_index

    X = rng.standard_normal((192, 16)).astype(np.float32)
    cfg = KNNConfig(k=3, backend="serial", query_bucket=16,
                    corpus_tile=64, query_tile=32)
    sess = ServeSession(build_index(X, cfg))
    sess.warm([16])
    ws = sess.warm_snapshot()
    assert ws["done"] is True and ws["total"] >= 1
    ws["ready"] = -99
    assert sess.warm_snapshot()["ready"] != -99
    list(sess.stream([X[:8], X[:12]]))
    st = sess.stats_snapshot()
    assert st["batches_retired"] == 2
    assert st["queries_served"] == 20
    assert st["rung"] == sess.rung
    st["tenants"].append("ghost")
    assert sess.stats_snapshot()["tenants"] == []


def test_atomic_write_publishes_whole_content(tmp_path):
    """utils.atomicio: concurrent writers + a polling reader — the
    reader only ever sees a COMPLETE document (the H4 property the
    ready-file/heartbeat/aotcache writers now share)."""
    from mpi_knn_tpu.utils.atomicio import atomic_write_text

    path = tmp_path / "ready"
    docs = [f"url-{i}" * 200 + "\n" for i in range(50)]
    seen = []
    stop = threading.Event()

    def reader():
        while not stop.is_set():
            try:
                seen.append(path.read_text())
            except OSError:
                pass

    t = threading.Thread(target=reader)
    t.start()
    try:
        for d in docs:
            atomic_write_text(path, d)
    finally:
        stop.set()
        t.join()
    assert path.read_text() == docs[-1]
    assert all(s in docs for s in seen if s)
    # no temp-file litter
    assert [p.name for p in tmp_path.iterdir()] == ["ready"]


def test_heartbeat_still_atomic_via_shared_helper(tmp_path):
    """The heartbeat writer refactored onto utils.atomicio keeps its
    protocol: strictly-increasing seq, readable mid-overwrite."""
    from mpi_knn_tpu.resilience.heartbeat import HeartbeatWriter, read_beat

    w = HeartbeatWriter(str(tmp_path / "beat.json"))
    assert w.beat("a") == 1
    assert w.beat("b") == 2
    doc = read_beat(str(tmp_path / "beat.json"))
    assert doc is not None and doc["seq"] == 2 and doc["label"] == "b"


def test_report_shape_and_save(tmp_path):
    """host_report.json carries schema, rules, roots, lock graph,
    waivers — the fields the check.sh gate pins."""
    rep = run_host_lint()
    path = rep.save(tmp_path)
    doc = json.loads(path.read_text())
    assert doc["schema_version"] == 1
    assert doc["source"] == "mpi_knn_tpu.analysis.host"
    assert set(doc["rules"]) == {
        "H1-lock-discipline", "H2-lock-order", "H3-confinement",
        "H4-atomic-publish",
    }
    assert doc["summary"]["targets"] == 7
    assert doc["summary"]["classes_checked"] >= 15
    s = doc["summary"]
    assert s["lock_graph_acyclic"] and s["findings"] == 0


@pytest.mark.parametrize("rule", [
    "H1-lock-discipline", "H2-lock-order", "H3-confinement",
    "H4-atomic-publish",
])
def test_each_rule_runs_clean_alone_on_production(rule):
    rep = run_host_lint(rule_names=[rule])
    assert rep.ok, [f.to_json() for f in rep.findings]
