"""The mixed-precision compress-and-rerank pipeline
(``KNNConfig.precision_policy="mixed"``, ops/rerank.py) against the f64
oracle and the exact policy — on CPU, where the compress pass's EXPLICIT
bf16 operand rounding makes the recall gate measure the same loss the TPU
MXU's single-pass DEFAULT dot would inflict (an implicit
``Precision.DEFAULT`` f32 dot is exact on CPU and would prove nothing).

The acceptance bar is the ISSUE 2 gate: recall@10 >= 0.999 vs the f64
oracle on all three backend families, plus the structural corners —
overfetch wider than the tile (the policy must degenerate to exact, not
crash or truncate), duplicate points whose compressed distances collapse at
the bf16 rounding boundary (the exact rerank must re-separate and
re-exclude them), and full id agreement with the exact policy when recall
is 1.0.
"""

import numpy as np
import pytest

from mpi_knn_tpu import KNNConfig, all_knn
from mpi_knn_tpu.ops.rerank import mixed_applies, overfetch_width
from tests.oracle import oracle_all_knn, recall_against_oracle

K = 10
RECALL_GATE = 0.999

BACKENDS = ["serial", "ring", "pallas"]


def _mnist_like(rng, m=512, d=96):
    """Integer-pixel-magnitude data (the headline workload's regime): large
    positive values whose CENTERED form genuinely loses mantissa bits in
    bf16 — the exact case the compress pass must survive via overfetch."""
    return np.rint(rng.random((m, d)) * 255.0).astype(np.float32)


@pytest.mark.parametrize("backend", BACKENDS)
def test_mixed_recall_gate_vs_f64_oracle(rng, backend):
    """The acceptance gate: recall@10 >= 0.999 vs the f64 oracle for every
    backend family, on data where bf16 compression is actually lossy."""
    X = _mnist_like(rng)
    got = all_knn(
        X,
        k=K,
        backend=backend,
        precision_policy="mixed",
        query_tile=64,
        corpus_tile=128,
    )
    want_d, want_i = oracle_all_knn(X, k=K)
    rec = recall_against_oracle(got.ids, want_d, want_i, K)
    assert rec >= RECALL_GATE, f"{backend}: recall@10 {rec} < {RECALL_GATE}"


@pytest.mark.parametrize("backend", BACKENDS)
@pytest.mark.parametrize("metric", ["l2", "cosine"])
def test_mixed_matches_oracle_both_metrics(rng, backend, metric):
    X = (rng.standard_normal((300, 32)) * 3).astype(np.float32)
    got = all_knn(
        X,
        k=8,
        backend=backend,
        metric=metric,
        precision_policy="mixed",
        query_tile=64,
        corpus_tile=128,
    )
    want_d, want_i = oracle_all_knn(X, k=8, metric=metric)
    assert recall_against_oracle(got.ids, want_d, want_i, 8) >= RECALL_GATE
    np.testing.assert_allclose(
        np.asarray(got.dists), want_d, rtol=1e-3, atol=1e-3
    )


@pytest.mark.parametrize("backend", BACKENDS)
def test_mixed_agrees_with_exact_at_full_recall(rng, backend):
    """When mixed recall vs exact is 1.0 the two policies must return the
    SAME id sets per query and matching distances — the rerank's exact
    recompute (same mask semantics, HIGHEST dot) is what guarantees the
    surviving candidates score identically to the exact pipeline."""
    X = (rng.standard_normal((256, 24)) * 4).astype(np.float32)
    kw = dict(k=6, backend=backend, query_tile=32, corpus_tile=128)
    exact = all_knn(X, precision_policy="exact", **kw)
    mixed = all_knn(X, precision_policy="mixed", **kw)
    ex_sets = [set(r.tolist()) for r in np.asarray(exact.ids)]
    mx_sets = [set(r.tolist()) for r in np.asarray(mixed.ids)]
    rec = np.mean(
        [len(a & b) / 6 for a, b in zip(ex_sets, mx_sets)]
    )
    if rec < 1.0:
        pytest.skip(f"recall vs exact is {rec} on this draw; the "
                    "agreement claim is conditional on 1.0")
    assert ex_sets == mx_sets
    # same candidates, same exact recompute -> same sorted distance rows
    np.testing.assert_allclose(
        np.sort(np.asarray(mixed.dists), axis=1),
        np.sort(np.asarray(exact.dists), axis=1),
        rtol=1e-6,
        atol=1e-6,
    )


@pytest.mark.parametrize("backend", BACKENDS)
def test_overfetch_wider_than_tile_degenerates_to_exact(rng, backend):
    """4k > c_tile: the compress pass could not drop a candidate, so the
    pipeline must fall back to the exact single pass — identical id sets to
    the exact policy, no shape errors at the boundary."""
    X = (rng.standard_normal((200, 16)) * 3).astype(np.float32)
    # k=10 -> overfetch 40 > corpus_tile=32 (pallas clamps its tile to 128
    # and 4k=40 < 128 there, so for pallas this exercises k*4 vs the
    # clamped tile instead — both sides of mixed_applies get covered)
    kw = dict(k=10, backend=backend, query_tile=32, corpus_tile=32)
    exact = all_knn(X, precision_policy="exact", **kw)
    mixed = all_knn(X, precision_policy="mixed", **kw)
    want_d, want_i = oracle_all_knn(X, k=10)
    assert recall_against_oracle(mixed.ids, want_d, want_i, 10) >= RECALL_GATE
    np.testing.assert_allclose(
        np.asarray(mixed.dists), np.asarray(exact.dists), rtol=1e-5,
        atol=1e-5,
    )
    assert not mixed_applies(10, 32)  # the XLA tile really is degenerate


def test_overfetch_width_boundaries():
    assert overfetch_width(4, 128) == 16
    assert overfetch_width(10, 32) == 32  # clamped to the tile
    assert mixed_applies(4, 128)
    assert not mixed_applies(10, 32)
    assert not mixed_applies(4, 16)  # 4k == c: nothing to drop


@pytest.mark.parametrize("backend", BACKENDS)
def test_duplicates_at_bf16_boundary_are_reseparated(rng, backend):
    """Exact duplicates plus near-duplicates that bf16 rounding COLLAPSES
    onto them: the compress pass sees identical keys for both (it cannot
    tell duplicate from near-twin), so only the exact rerank can (a)
    re-exclude the true duplicate by the zero rule and (b) keep the
    near-twin as the genuine nearest neighbor."""
    X = _mnist_like(rng, m=128, d=64)
    X[7] = X[3]  # exact duplicate pair
    # near-twin of row 11: one pixel nudged by 8 → exact d² = 64, above
    # the relative zero threshold (~1e-6·‖pair‖² ≈ 0.7 here) but orders
    # below both genuine neighbor distances (~1e6) AND the compress key's
    # bf16 noise floor (xy products ~3e5, bf16 ulp ≈ 2^-8 relative →
    # O(1e3) key error) — so the compressed keys of the duplicate and the
    # near-twin collapse and only the exact rerank can tell them apart
    X[42] = X[11]
    X[42, 0] += 8.0
    got = all_knn(
        X,
        k=6,
        backend=backend,
        precision_policy="mixed",
        query_tile=32,
        corpus_tile=128,
    )
    ids = np.asarray(got.ids)
    dists = np.asarray(got.dists)
    # duplicate pair excluded by the zero rule, on exact values
    assert 7 not in ids[3] and 3 not in ids[7]
    # near-twin kept, ranked first, at its exact (nonzero) distance —
    # a compressed-key-only pipeline could return it at key noise scale
    # (O(1e3)) or drop it as zero; the rerank restores d² = 64 exactly
    assert ids[11][0] == 42 and ids[42][0] == 11
    assert 1.0 < dists[11][0] < 1000.0


@pytest.mark.parametrize("schedule", ["stream", "twolevel"])
def test_mixed_both_merge_schedules(rng, schedule):
    """The policy lives in the per-tile reduction, below the schedule split
    — both schedules must pass the gate and agree with each other."""
    X = _mnist_like(rng, m=300, d=48)
    a = all_knn(X, k=K, backend="serial", precision_policy="mixed",
                merge_schedule=schedule, query_tile=64, corpus_tile=128)
    want_d, want_i = oracle_all_knn(X, k=K)
    assert recall_against_oracle(a.ids, want_d, want_i, K) >= RECALL_GATE


@pytest.mark.parametrize("variant", ["tiles", "sweep"])
def test_mixed_pallas_variants(rng, variant):
    """Both fused-kernel shapes run the in-kernel compress + overfetch and
    the XLA exact finish."""
    X = _mnist_like(rng, m=256, d=64)
    got = all_knn(X, k=K, backend="pallas", pallas_variant=variant,
                  precision_policy="mixed", query_tile=64, corpus_tile=128)
    want_d, want_i = oracle_all_knn(X, k=K)
    assert recall_against_oracle(got.ids, want_d, want_i, K) >= RECALL_GATE


def test_mixed_ring_resumable_checkpoint_layout_unchanged(rng, tmp_path):
    """The carry stays exact f32 under mixed, so a kill-and-resume run is
    bit-identical to an uninterrupted one — same property the exact policy
    guarantees, now under the two-pass tile reduction."""
    from mpi_knn_tpu.backends.ring_resumable import all_knn_ring_resumable

    X = _mnist_like(rng, m=256, d=32)
    qids = np.arange(256, dtype=np.int32)
    cfg = KNNConfig(k=5, backend="ring", precision_policy="mixed",
                    query_tile=16, corpus_tile=128)
    full_d, full_i = all_knn_ring_resumable(
        X, X, qids, cfg, checkpoint_dir=None
    )
    ck = tmp_path / "ck"
    all_knn_ring_resumable(
        X, X, qids, cfg, checkpoint_dir=str(ck), stop_after_rounds=3
    )
    res_d, res_i = all_knn_ring_resumable(
        X, X, qids, cfg, checkpoint_dir=str(ck)
    )
    np.testing.assert_array_equal(np.asarray(full_d), np.asarray(res_d))
    np.testing.assert_array_equal(np.asarray(full_i), np.asarray(res_i))


def test_mixed_config_validation():
    with pytest.raises(ValueError, match="dtype"):
        KNNConfig(precision_policy="mixed", dtype="bfloat16")
    with pytest.raises(ValueError, match="matmul_precision"):
        KNNConfig(precision_policy="mixed", matmul_precision="high")
    with pytest.raises(ValueError, match="precision_policy"):
        KNNConfig(precision_policy="fast")
    # the valid combination constructs
    KNNConfig(precision_policy="mixed")


def test_r3_mixed_contract_catches_violations():
    """The lint side of the acceptance gate, negatively: a mixed-labeled
    program whose dots do NOT follow the declared contract (no DEFAULT
    compress dot / no HIGHEST rerank dot / a third precision) must be
    flagged by R3 through the production rule path."""
    from mpi_knn_tpu.analysis import engine, lowering
    from mpi_knn_tpu.analysis import rules as rules_mod

    def ctx():
        return engine.LintContext(
            target=lowering.LintTarget("serial", "l2", "float32", "mixed"),
            cfg=KNNConfig(k=4, query_tile=8, corpus_tile=32,
                          precision_policy="mixed"),
            meta={"q_tile": 8, "c_tile": 32, "acc_bytes": 4},
        )

    r3 = [r for r in rules_mod.RULES if r.name == "R3-dtype"]

    def run(body):
        mod = f"""\
HloModule m, entry_computation_layout={{(f32[4,8]{{1,0}})->f32[4,4]{{1,0}}}}

ENTRY %main.1 (a.1: f32[4,8]) -> f32[4,4] {{
  %a.1 = f32[4,8]{{1,0}} parameter(0)
{body}
}}
"""
        findings, _ = engine.run_rules({"before_opt": mod}, ctx(), r3)
        return findings

    dot = ("  %d{n}.1 = f32[4,4]{{1,0}} dot(%a.1, %a.1), "
           "lhs_contracting_dims={{1}}, rhs_contracting_dims={{1}}{attr}\n")
    d_def = dot.format(n=1, attr="")
    d_def2 = dot.format(n=2, attr="")
    d_hi = dot.format(n=3, attr=", operand_precision={highest,highest}")
    d_high = dot.format(n=4, attr=", operand_precision={high,high}")
    root = "  ROOT %r.1 = f32[4,4]{1,0} add(%d1.1, %d1.1)"

    # the declared shape: one DEFAULT + one HIGHEST — clean
    assert not run(d_def + d_hi + root)
    # missing rerank dot
    assert any("no highest" in f.message.lower()
               for f in run(d_def + root))
    # missing compress dot
    assert any("no default" in f.message.lower()
               for f in run(d_hi + root))
    # two compress dots in one computation
    assert any("2 default" in f.message.lower()
               for f in run(d_def + d_def2 + d_hi + root))
    # a third precision (HIGH) is neither compress nor rerank
    assert any("'high'" in f.message for f in run(d_def + d_hi + d_high + root))


def test_full_mixed_lint_matrix_is_clean():
    """The positive lint acceptance criterion: every mixed backend × metric
    cell lowers and passes all rules — R3 certifying exactly one DEFAULT
    compress dot per tile computation and a HIGHEST rerank dot (zero of
    either is itself a finding, so 'ok' is non-vacuous)."""
    from mpi_knn_tpu.analysis import engine, lowering

    targets = [t for t in lowering.default_targets() if t.policy == "mixed"]
    assert targets, "mixed cells missing from the default lint sweep"
    for t in targets:
        res = engine.lint_target(t)
        assert res.skipped is None, (t.label, res.skipped)
        assert res.ok, (t.label, [f.message for f in res.findings])
