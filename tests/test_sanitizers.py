"""Sanitizer-style checks (SURVEY.md §6 "Race detection / sanitizers"):
the reference has real data races and no tooling (Q2); here the functional
model is race-free by construction, and these tests run the numerics under
``jax_debug_nans`` (the JAX analog of a sanitizer pass — any NaN produced
inside a jitted computation raises immediately) plus dtype sweeps that pin
every backend to the serial ground truth.
"""

from pathlib import Path

import numpy as np
import pytest

from mpi_knn_tpu import KNNConfig, all_knn, knn_classify

_REPO = Path(__file__).resolve().parents[1]


def _data(rng, m=64, d=12):
    return rng.standard_normal((m, d)).astype(np.float32)


def test_no_nans_under_debug_nans(rng, debug_nans):
    """The full pipeline (distances -> masks -> top-k -> vote) must not
    produce NaNs even with duplicate rows and zero vectors in the corpus.
    +inf sentinels are fine; NaN would poison comparisons silently.
    The flag toggle lives in the ``debug_nans`` conftest fixture so a
    mid-test crash can never leak it into later tests."""
    X = _data(rng)
    X[10] = X[3]  # exact duplicate (zero-distance path)
    X[20] = 0.0  # zero vector (cosine normalization edge)
    y = rng.integers(0, 4, size=len(X)).astype(np.int32)
    for metric in ("l2", "cosine"):
        res = all_knn(X, config=KNNConfig(k=5, metric=metric,
                                          query_tile=16, corpus_tile=32))
        cls = knn_classify(res, y, num_classes=4)
        np.asarray(cls.predictions)


@pytest.mark.parametrize("dtype", ["float32", "bfloat16", "float64"])
@pytest.mark.parametrize("backend", ["serial", "ring-overlap"])
def test_dtype_sweep_recall(rng, dtype, backend):
    """Every (dtype, backend) combination reaches near-perfect recall vs
    the f64 serial ground truth on well-separated data (bf16 may flip true
    near-ties, so the gate is recall, not bit equality)."""
    from mpi_knn_tpu.utils.report import recall_at_k

    centers = rng.standard_normal((8, 12)) * 8.0
    labels = rng.integers(0, 8, size=64)
    X = (centers[labels] + rng.standard_normal((64, 12)) * 0.1).astype(
        np.float32
    )
    truth = all_knn(
        X, config=KNNConfig(k=5, dtype="float64", backend="serial",
                            query_tile=16, corpus_tile=32)
    )
    got = all_knn(
        X, config=KNNConfig(k=5, dtype=dtype, backend=backend,
                            query_tile=16, corpus_tile=32)
    )
    rec = recall_at_k(np.asarray(got.ids), np.asarray(truth.ids))
    assert rec >= (0.97 if dtype == "bfloat16" else 0.999), rec


def _build_sanitizer_lib_or_skip(so_name: str):
    """Build ONE sanitizer lib (per-artifact, mirroring data/_native.py:
    a failure in another library's rule must not block this one), or skip
    when the toolchain is absent. Shared by the ASan and UBSan tests."""
    import subprocess

    mk = subprocess.run(
        ["make", "-C", "native", f"build/{so_name}"],
        capture_output=True, text=True, cwd=_REPO, timeout=120,
    )
    if mk.returncode != 0:
        pytest.skip(f"no sanitizer toolchain: {mk.stderr[-200:]}")
    return _REPO / "native" / "build" / so_name


def _asan_runtime_or_skip(so_name: str):
    """Build + locate the matching ASan runtime, or skip. The runtime must
    come from the SAME compiler family the Makefile used ($(CXX)); a
    gcc-located libasan under a clang-built .so aborts at interceptor
    init."""
    import os
    import subprocess

    _build_sanitizer_lib_or_skip(so_name)
    cxx = os.environ.get("CXX", "g++")
    if "clang" in cxx:
        locator = [cxx, "-print-file-name=libclang_rt.asan-x86_64.so"]
    else:
        locator = [cxx.replace("g++", "gcc") if "g++" in cxx else cxx,
                   "-print-file-name=libasan.so"]
    try:
        libasan = subprocess.run(
            locator, capture_output=True, text=True, timeout=30,
        ).stdout.strip()
    except (OSError, subprocess.SubprocessError):
        pytest.skip(f"cannot locate the ASan runtime via {locator[0]}")
    if not os.path.isabs(libasan):
        # the compiler echoes the bare name back when it can't find the
        # runtime; LD_PRELOADing that string silently does nothing and the
        # ASan .so then aborts at load — skip instead
        pytest.skip(f"{locator[0]} has no ASan runtime")
    return libasan


def _tsan_runtime_or_skip(so_name: str):
    """Build + locate the matching TSan runtime, or skip (toolchains
    without -fsanitize=thread fail the make and skip there). Same
    same-compiler-family rule as ASan: a gcc libtsan under a clang-built
    .so aborts at interceptor init."""
    import os
    import subprocess

    _build_sanitizer_lib_or_skip(so_name)
    cxx = os.environ.get("CXX", "g++")
    if "clang" in cxx:
        locator = [cxx, "-print-file-name=libclang_rt.tsan-x86_64.so"]
    else:
        locator = [cxx.replace("g++", "gcc") if "g++" in cxx else cxx,
                   "-print-file-name=libtsan.so"]
    try:
        libtsan = subprocess.run(
            locator, capture_output=True, text=True, timeout=30,
        ).stdout.strip()
    except (OSError, subprocess.SubprocessError):
        pytest.skip(f"cannot locate the TSan runtime via {locator[0]}")
    if not os.path.isabs(libtsan):
        pytest.skip(f"{locator[0]} has no TSan runtime")
    return libtsan


def _run_sanitized(code: str, **env_extra):
    import os
    import subprocess
    import sys

    return subprocess.run(
        [sys.executable, "-c", code],
        env=dict(os.environ, **env_extra),
        capture_output=True, text=True, cwd=_REPO, timeout=300,
    )


def _scipy_mat_dir_or_skip():
    import os

    data_dir = None
    try:
        import scipy.io as sio
        data_dir = os.path.join(
            os.path.dirname(sio.matlab.__file__), "tests", "data"
        )
    except ImportError:
        pass
    if not data_dir or not os.path.isdir(data_dir):
        pytest.skip("scipy matlab fixtures unavailable")
    return data_dir


def _mat_sweep_code(lib_path, data_dir) -> str:
    """The genuine-MATLAB-fixture sweep (110 files: v5 parsed, v4/
    big-endian/object rejected) over the PRODUCTION read loop, against a
    sanitizer-built lib. Shared by the ASan and UBSan tests."""
    return f"""
import ctypes, glob
from mpi_knn_tpu.data.matfile import read_mat_native
lib = ctypes.CDLL({str(lib_path)!r})
n_ok = n_err = 0
for f in sorted(glob.glob({data_dir!r} + '/*.mat')):
    try:
        read_mat_native(f, lib=lib)  # the PRODUCTION read loop
        n_ok += 1
    except ValueError:
        n_err += 1
print('PARSED', n_ok, 'REJECTED', n_err)
assert n_ok >= 70 and n_err >= 25
"""


def test_native_mat_reader_asan_clean_on_genuine_matlab_files():
    """The C++ MAT parser, built with AddressSanitizer, sweeps every genuine
    MATLAB-written fixture scipy ships with zero sanitizer aborts — the
    native-code analog of the Q2 race-tooling the reference lacked.
    Subprocess: ASan must be LD_PRELOADed before the interpreter starts."""
    libasan = _asan_runtime_or_skip("libtknn_matio_asan.so")
    data_dir = _scipy_mat_dir_or_skip()
    code = _mat_sweep_code(
        _REPO / "native/build/libtknn_matio_asan.so", data_dir
    )
    r = _run_sanitized(code, LD_PRELOAD=libasan,
                       ASAN_OPTIONS="detect_leaks=0")
    assert r.returncode == 0, (r.stdout + r.stderr)[-2000:]
    assert "PARSED" in r.stdout


def test_native_mat_reader_ubsan_clean_on_genuine_matlab_files():
    """Same sweep against the UBSan build: signed overflow, misaligned or
    out-of-range loads in the tag/dimension arithmetic abort the
    subprocess (-fno-sanitize-recover). No preload needed — libubsan is a
    NEEDED dep of the .so."""
    lib = _build_sanitizer_lib_or_skip("libtknn_matio_ubsan.so")
    data_dir = _scipy_mat_dir_or_skip()
    r = _run_sanitized(
        _mat_sweep_code(lib, data_dir),
        UBSAN_OPTIONS="halt_on_error=1,print_stacktrace=1",
    )
    assert r.returncode == 0, (r.stdout + r.stderr)[-2000:]
    assert "PARSED" in r.stdout


def _vecs_sweep_code(lib_path) -> str:
    """fvecs/bvecs/ivecs sweep: valid files plus truncated/absurd-dim/
    inconsistent mutants through the PRODUCTION read loop. Shared by the
    ASan and UBSan tests."""
    return f"""
import ctypes, struct
import numpy as np
from pathlib import Path
import tempfile
from mpi_knn_tpu.data.vecs import read_vecs_native
lib = ctypes.CDLL({str(lib_path)!r})
with tempfile.TemporaryDirectory() as td:
    tmp = Path(td)
    rng = np.random.default_rng(0)
    ok = rejected = 0
    def write(path, arr, comp):
        with open(path, 'wb') as f:
            for row in arr:
                f.write(struct.pack('<i', len(row)))
                f.write(np.asarray(row, dtype=comp).tobytes())
    X = rng.standard_normal((40, 12)).astype(np.float32)
    write(tmp / 'a.fvecs', X, np.float32)
    write(tmp / 'b.bvecs', (np.abs(X) * 10 % 200), np.uint8)
    write(tmp / 'c.ivecs', (X * 100), np.int32)
    for f in ('a.fvecs', 'b.bvecs', 'c.ivecs'):
        got = read_vecs_native(tmp / f, lib=lib)
        assert got is not None and got.shape[0] == 40
        ok += 1
    # mutants: truncated mid-row, absurd dim, inconsistent dims
    (tmp / 'trunc.fvecs').write_bytes((tmp / 'a.fvecs').read_bytes()[:-7])
    (tmp / 'bigdim.fvecs').write_bytes(struct.pack('<i', 1 << 30) + b'xxxx')
    good = (tmp / 'a.fvecs').read_bytes()
    (tmp / 'mixed.fvecs').write_bytes(good + struct.pack('<i', 5) + b'\\0' * 20)
    for f in ('trunc.fvecs', 'bigdim.fvecs', 'mixed.fvecs'):
        try:
            read_vecs_native(tmp / f, lib=lib)
        except ValueError:
            rejected += 1
    print('VECS_OK', ok, 'VECS_REJECTED', rejected)
    assert ok == 3 and rejected == 3
"""


def test_native_vecs_reader_asan_clean():
    libasan = _asan_runtime_or_skip("libtknn_vecsio_asan.so")
    code = _vecs_sweep_code(_REPO / "native/build/libtknn_vecsio_asan.so")
    r = _run_sanitized(code, LD_PRELOAD=libasan,
                       ASAN_OPTIONS="detect_leaks=0")
    assert r.returncode == 0, (r.stdout + r.stderr)[-2000:]
    assert "VECS_OK 3" in r.stdout


def test_native_vecs_reader_ubsan_clean():
    """The mutant sweep is where UB hides in a reader: a 1<<30 dim header
    multiplied into a byte count is exactly the signed-overflow class
    UBSan exists for."""
    lib = _build_sanitizer_lib_or_skip("libtknn_vecsio_ubsan.so")
    r = _run_sanitized(
        _vecs_sweep_code(lib),
        UBSAN_OPTIONS="halt_on_error=1,print_stacktrace=1",
    )
    assert r.returncode == 0, (r.stdout + r.stderr)[-2000:]
    assert "VECS_OK 3" in r.stdout


def _threaded_vecs_sweep_code(lib_path) -> str:
    """ISSUE 13 TSan sweep: 8 threads hammer the PRODUCTION fvecs/bvecs
    read loop concurrently over shared files through ONE dlopened
    sanitizer lib. The readers are documented stateless — this is the
    machine check: any hidden shared state (a static scratch buffer, an
    unlocked errno-style flag) is a data race TSan aborts on."""
    return f"""
import ctypes, struct, threading
import numpy as np
from pathlib import Path
import tempfile
from mpi_knn_tpu.data.vecs import read_vecs_native
lib = ctypes.CDLL({str(lib_path)!r})
with tempfile.TemporaryDirectory() as td:
    tmp = Path(td)
    rng = np.random.default_rng(0)
    def write(path, arr, comp):
        with open(path, 'wb') as f:
            for row in arr:
                f.write(struct.pack('<i', len(row)))
                f.write(np.asarray(row, dtype=comp).tobytes())
    X = rng.standard_normal((64, 24)).astype(np.float32)
    write(tmp / 'a.fvecs', X, np.float32)
    write(tmp / 'b.bvecs', (np.abs(X) * 10 % 200), np.uint8)
    (tmp / 'trunc.fvecs').write_bytes((tmp / 'a.fvecs').read_bytes()[:-5])
    ok = [0] * 8
    rejected = [0] * 8
    def sweep(i):
        for _ in range(25):
            a = read_vecs_native(tmp / 'a.fvecs', lib=lib)
            b = read_vecs_native(tmp / 'b.bvecs', lib=lib)
            assert a.shape == (64, 24) and b.shape == (64, 24)
            ok[i] += 2
            try:
                read_vecs_native(tmp / 'trunc.fvecs', lib=lib)
            except ValueError:
                rejected[i] += 1
    threads = [threading.Thread(target=sweep, args=(i,)) for i in range(8)]
    for t in threads: t.start()
    for t in threads: t.join()
    print('TSAN_OK', sum(ok), 'TSAN_REJECTED', sum(rejected))
    assert sum(ok) == 8 * 50 and sum(rejected) == 8 * 25
"""


def test_native_vecs_reader_tsan_clean_under_threaded_sweep():
    """The fvecs/bvecs reader, built with ThreadSanitizer, survives a
    concurrent 8-thread sweep with zero race reports (halt_on_error
    turns any report into a non-zero exit). Skip-guarded like the UBSan
    sweep when the toolchain lacks -fsanitize=thread."""
    libtsan = _tsan_runtime_or_skip("libtknn_vecsio_tsan.so")
    code = _threaded_vecs_sweep_code(
        _REPO / "native/build/libtknn_vecsio_tsan.so"
    )
    r = _run_sanitized(code, LD_PRELOAD=libtsan,
                       TSAN_OPTIONS="halt_on_error=1,report_bugs=1")
    assert r.returncode == 0, (r.stdout + r.stderr)[-2000:]
    assert "TSAN_OK 400" in r.stdout
    assert "WARNING: ThreadSanitizer" not in r.stderr


def test_native_mat_reader_tsan_clean_under_threaded_sweep():
    """The MAT v5 parser under the same treatment: 4 threads × the
    genuine-MATLAB fixture sweep, concurrently, one shared lib."""
    libtsan = _tsan_runtime_or_skip("libtknn_matio_tsan.so")
    data_dir = _scipy_mat_dir_or_skip()
    code = f"""
import ctypes, glob, threading
from mpi_knn_tpu.data.matfile import read_mat_native
lib = ctypes.CDLL({str(_REPO / 'native/build/libtknn_matio_tsan.so')!r})
files = sorted(glob.glob({data_dir!r} + '/*.mat'))[:40]
totals = [0] * 4
def sweep(i):
    for f in files:
        try:
            read_mat_native(f, lib=lib)
        except ValueError:
            pass
        totals[i] += 1
threads = [threading.Thread(target=sweep, args=(i,)) for i in range(4)]
for t in threads: t.start()
for t in threads: t.join()
print('MAT_TSAN_OK', sum(totals))
assert sum(totals) == 4 * len(files)
"""
    r = _run_sanitized(code, LD_PRELOAD=libtsan,
                       TSAN_OPTIONS="halt_on_error=1,report_bugs=1")
    assert r.returncode == 0, (r.stdout + r.stderr)[-2000:]
    assert "MAT_TSAN_OK" in r.stdout
    assert "WARNING: ThreadSanitizer" not in r.stderr


def test_logs_prefix_and_levels(capsys):
    import logging

    from mpi_knn_tpu.utils.logs import log, setup_logging

    setup_logging(verbosity=1)
    log.info("hello")
    err = capsys.readouterr().err
    assert "[host0/1]" in err and "hello" in err
    # -q drops INFO
    setup_logging(verbosity=1, quiet=True)
    log.info("silent")
    assert "silent" not in capsys.readouterr().err
    # repeated setup must not duplicate handlers
    setup_logging(verbosity=1)
    setup_logging(verbosity=1)
    log.info("once")
    assert capsys.readouterr().err.count("once") == 1
    assert log.level == logging.INFO
