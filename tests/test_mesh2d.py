"""2-D (dp × ring) mesh: queries shard over every device, the corpus rings
within each dp group (SURVEY.md §2a — the strategy mix the reference's single
MPI axis cannot express). Property: any mesh shape == serial, for both ring
schedules, all-pairs and query mode.
"""

import jax
import numpy as np
import pytest

from mpi_knn_tpu import KNNConfig, all_knn
from mpi_knn_tpu.parallel.mesh import make_mesh2d


def _data(rng, m=96, d=12):
    return rng.standard_normal((m, d)).astype(np.float32)


@pytest.mark.parametrize("dp,ring", [(2, 4), (4, 2), (8, 1), (1, 8)])
@pytest.mark.parametrize("overlap", [True, False])
def test_mesh2d_matches_serial(rng, dp, ring, overlap):
    X = _data(rng)
    cfg = KNNConfig(
        k=5,
        backend="ring-overlap" if overlap else "ring",
        query_tile=4,
        corpus_tile=8,
    )
    mesh = make_mesh2d(dp, ring)
    want = all_knn(X, config=cfg.replace(backend="serial"))
    got = all_knn(X, config=cfg, mesh=mesh)
    np.testing.assert_array_equal(np.asarray(want.ids), np.asarray(got.ids))
    np.testing.assert_allclose(
        np.asarray(want.dists), np.asarray(got.dists), rtol=1e-5
    )


def test_mesh2d_query_mode(rng):
    X, Q = _data(rng, m=64), _data(rng, m=40)
    cfg = KNNConfig(k=3, backend="ring-overlap", query_tile=4, corpus_tile=8)
    mesh = make_mesh2d(2, 4)
    want = all_knn(X, queries=Q, config=cfg.replace(backend="serial"))
    got = all_knn(X, queries=Q, config=cfg, mesh=mesh)
    np.testing.assert_array_equal(np.asarray(want.ids), np.asarray(got.ids))


def test_mesh2d_uneven_sizes(rng):
    """Neither dp·ring | nq nor ring | m: padding + masking must cover it."""
    X = _data(rng, m=61)
    cfg = KNNConfig(k=4, backend="ring", query_tile=4, corpus_tile=8)
    mesh = make_mesh2d(2, 4)
    want = all_knn(X, config=cfg.replace(backend="serial"))
    got = all_knn(X, config=cfg, mesh=mesh)
    np.testing.assert_array_equal(np.asarray(want.ids), np.asarray(got.ids))


def test_mesh2d_corpus_memory_scales_with_ring():
    """The corpus shards over the ring axis only: per-device corpus bytes
    shrink with ring size, not with dp (the documented capacity tradeoff)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = make_mesh2d(2, 4)
    x = np.zeros((64, 8), np.float32)
    xs = jax.device_put(x, NamedSharding(mesh, P("ring")))
    shard_rows = {s.data.shape[0] for s in xs.addressable_shards}
    assert shard_rows == {64 // 4}


def test_make_mesh2d_validates():
    with pytest.raises(ValueError):
        make_mesh2d(3, 4)  # 12 > 8 visible devices
