"""2-D (dp × ring) mesh: queries shard over every device, the corpus rings
within each dp group (SURVEY.md §2a — the strategy mix the reference's single
MPI axis cannot express). Property: any mesh shape == serial, for both
rotation schedules (uni/bidir), all-pairs and query mode — under the overlap
sequencing only: the blocking schedule is a HARD ERROR on any 2-axis mesh
(the barrier can pin only the block there; VERDICT r5 weak #3).
"""

import jax
import numpy as np
import pytest

from mpi_knn_tpu import KNNConfig, all_knn
from mpi_knn_tpu.parallel.mesh import make_mesh2d


def _data(rng, m=96, d=12):
    return rng.standard_normal((m, d)).astype(np.float32)


@pytest.mark.parametrize("dp,ring", [(2, 4), (4, 2), (8, 1), (1, 8)])
@pytest.mark.parametrize("schedule", ["uni", "bidir"])
def test_mesh2d_matches_serial(rng, dp, ring, schedule):
    X = _data(rng)
    cfg = KNNConfig(
        k=5,
        backend="ring-overlap",
        query_tile=4,
        corpus_tile=8,
        ring_schedule=schedule,
    )
    mesh = make_mesh2d(dp, ring)
    want = all_knn(X, config=cfg.replace(backend="serial"))
    got = all_knn(X, config=cfg, mesh=mesh)
    np.testing.assert_array_equal(np.asarray(want.ids), np.asarray(got.ids))
    np.testing.assert_allclose(
        np.asarray(want.dists), np.asarray(got.dists), rtol=1e-5
    )


@pytest.mark.parametrize("schedule", ["uni", "bidir"])
def test_mesh2d_blocking_is_a_hard_error(rng, schedule):
    """VERDICT r5 weak #3, closed: overlap=False on a dp×ring mesh used to
    run the overlap schedule silently (the barrier pinned only the block —
    varying-axes typing). Now it is a hard error naming the 1-D ring as the
    only defined blocking A/B object — on ANY 2-axis mesh, dp=1 included,
    and through the resumable driver too."""
    from mpi_knn_tpu.backends.ring_resumable import all_knn_ring_resumable

    X = _data(rng, m=32)
    cfg = KNNConfig(k=3, backend="ring", query_tile=4, corpus_tile=8,
                    ring_schedule=schedule)
    for mesh in (make_mesh2d(2, 4), make_mesh2d(1, 8)):
        with pytest.raises(ValueError, match="1-D ring"):
            all_knn(X, config=cfg, mesh=mesh)
        with pytest.raises(ValueError, match="1-D ring"):
            all_knn_ring_resumable(
                X, X, np.arange(len(X), dtype=np.int32), cfg,
                mesh=mesh, overlap=False,
            )


def test_mesh2d_query_mode(rng):
    X, Q = _data(rng, m=64), _data(rng, m=40)
    cfg = KNNConfig(k=3, backend="ring-overlap", query_tile=4, corpus_tile=8)
    mesh = make_mesh2d(2, 4)
    want = all_knn(X, queries=Q, config=cfg.replace(backend="serial"))
    got = all_knn(X, queries=Q, config=cfg, mesh=mesh)
    np.testing.assert_array_equal(np.asarray(want.ids), np.asarray(got.ids))


def test_mesh2d_uneven_sizes(rng):
    """Neither dp·ring | nq nor ring | m: padding + masking must cover it.
    (ring-overlap: the blocking schedule is a hard error on 2-D meshes.)"""
    X = _data(rng, m=61)
    cfg = KNNConfig(k=4, backend="ring-overlap", query_tile=4, corpus_tile=8)
    mesh = make_mesh2d(2, 4)
    want = all_knn(X, config=cfg.replace(backend="serial"))
    got = all_knn(X, config=cfg, mesh=mesh)
    np.testing.assert_array_equal(np.asarray(want.ids), np.asarray(got.ids))


def test_mesh2d_corpus_memory_scales_with_ring():
    """The corpus shards over the ring axis only: per-device corpus bytes
    shrink with ring size, not with dp (the documented capacity tradeoff)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = make_mesh2d(2, 4)
    x = np.zeros((64, 8), np.float32)
    xs = jax.device_put(x, NamedSharding(mesh, P("ring")))
    shard_rows = {s.data.shape[0] for s in xs.addressable_shards}
    assert shard_rows == {64 // 4}


def test_make_mesh2d_validates():
    with pytest.raises(ValueError):
        make_mesh2d(3, 4)  # 12 > 8 visible devices
