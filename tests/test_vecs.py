"""fvecs/bvecs/ivecs reader: native C++ and NumPy paths agree, errors are
clean, and the CLI accepts the format end-to-end (the SIFT1M on-disk format,
BASELINE.md)."""

import struct
import subprocess
import sys

import numpy as np
import pytest

from mpi_knn_tpu.data.vecs import (
    load_native_lib,
    read_vecs,
    read_vecs_native,
    read_vecs_numpy,
)


def write_vecs(path, arr, kind):
    """Tiny writer for test fixtures (the real files come from the TexMex
    distribution; the reader is clean-room against the published format)."""
    comp = {"f": "<f4", "b": "u1", "i": "<i4"}[kind]
    with open(path, "wb") as f:
        for row in arr:
            f.write(struct.pack("<i", len(row)))
            f.write(np.asarray(row, dtype=comp).tobytes())


@pytest.fixture
def fvecs_file(tmp_path, rng):
    X = rng.standard_normal((20, 8)).astype(np.float32)
    p = tmp_path / "base.fvecs"
    write_vecs(p, X, "f")
    return p, X


def test_fvecs_roundtrip(fvecs_file):
    p, X = fvecs_file
    np.testing.assert_array_equal(read_vecs_numpy(p), X)


def test_bvecs_widen(tmp_path, rng):
    B = rng.integers(0, 256, size=(12, 16)).astype(np.uint8)
    p = tmp_path / "base.bvecs"
    write_vecs(p, B, "b")
    out = read_vecs_numpy(p)
    assert out.dtype == np.float32
    np.testing.assert_array_equal(out, B.astype(np.float32))


def test_ivecs_groundtruth(tmp_path, rng):
    G = rng.integers(0, 1000, size=(7, 10)).astype(np.int32)
    p = tmp_path / "gt.ivecs"
    write_vecs(p, G, "i")
    out = read_vecs_numpy(p)
    assert out.dtype == np.int32
    np.testing.assert_array_equal(out, G)


def test_native_matches_numpy(fvecs_file):
    p, X = fvecs_file
    if load_native_lib() is None:
        pytest.skip("native toolchain unavailable")
    native = read_vecs_native(p)
    np.testing.assert_array_equal(native, read_vecs_numpy(p))
    # limit honored
    np.testing.assert_array_equal(read_vecs_native(p, limit=5), X[:5])


def test_limit_and_dispatch(fvecs_file):
    p, X = fvecs_file
    np.testing.assert_array_equal(read_vecs(p, limit=3), X[:3])


def test_inconsistent_dim_rejected(tmp_path, rng):
    p = tmp_path / "bad.fvecs"
    with open(p, "wb") as f:
        f.write(struct.pack("<i", 4) + np.zeros(4, "<f4").tobytes())
        f.write(struct.pack("<i", 5) + np.zeros(5, "<f4").tobytes())
    with pytest.raises(ValueError, match="dimension|stride"):
        read_vecs_numpy(p)
    if load_native_lib() is not None:
        with pytest.raises(ValueError, match="inconsistent dimension"):
            read_vecs_native(p)


def test_truncated_rejected(tmp_path):
    p = tmp_path / "trunc.fvecs"
    with open(p, "wb") as f:
        f.write(struct.pack("<i", 8) + np.zeros(3, "<f4").tobytes())
    with pytest.raises(ValueError):
        read_vecs_numpy(p)
    if load_native_lib() is not None:
        with pytest.raises(ValueError, match="truncated"):
            read_vecs_native(p)


def test_unknown_suffix():
    with pytest.raises(ValueError, match="fvecs"):
        read_vecs_numpy("corpus.dat")


def test_cli_fvecs(tmp_path, rng, fvecs_file):
    p, X = fvecs_file
    r = subprocess.run(
        [sys.executable, "-m", "mpi_knn_tpu", "--data", str(p), "--k", "3",
         "--backend", "serial", "--platform", "cpu", "-q"],
        capture_output=True, text=True, cwd="/root/repo", timeout=300,
    )
    assert r.returncode == 0, r.stderr[-2000:]


def test_truncated_beyond_limit_ok(tmp_path, rng):
    """A file truncated AFTER the requested limit reads fine on both paths
    (partially-downloaded corpora are usable up to the valid prefix)."""
    X = rng.standard_normal((6, 4)).astype(np.float32)
    p = tmp_path / "partial.fvecs"
    write_vecs(p, X, "f")
    with open(p, "ab") as f:
        f.write(struct.pack("<i", 4) + b"\x00" * 5)  # torn trailing row
    np.testing.assert_array_equal(read_vecs_numpy(p, limit=6), X)
    if load_native_lib() is not None:
        np.testing.assert_array_equal(read_vecs_native(p, limit=6), X)
    # but reading past the tear still errors on both
    with pytest.raises(ValueError):
        read_vecs_numpy(p)
    if load_native_lib() is not None:
        with pytest.raises(ValueError):
            read_vecs_native(p)
    # limit=0 agrees across paths
    assert read_vecs_numpy(p, limit=0).shape == (0, 0)
    if load_native_lib() is not None:
        assert read_vecs_native(p, limit=0).shape == (0, 0)
