"""The quantization layer (ISSUE 9): block-scaled int8 ring transfer and
int8/int4 at-rest clustered stores (``ops/quant.py``).

Four layers:

- **primitive properties** — quant/dequant round trip within scale/2 per
  element, nibble pack/unpack exact, zero-block and all-negative-block
  edge cases, odd-dim padding;
- **transfer** — the int8 ring gate: recall@10 vs the f64 oracle on both
  rotation schedules with uni ≡ bidir bit-identically, the resumable
  kill/resume parity, serving parity + zero steady-state compiles, and
  the R4 wire-payload acceptance (ppermute bytes ≤ 0.27× the f32 cell at
  d=128, read from the lowered HLO);
- **at rest** — int8/int4 clustered stores: recall floors, save/load and
  shard/unshard bit-identity, sharded search parity, byte cuts against
  the same-layout f32 store, the SIFT-32k int4 acceptance gate;
- **config** — int8 transfer is refused under precision_policy="exact"
  (no rerank to absorb the quantization) and the validation message
  enumerates the accepted set.

On recall bars: these are MEASURED bars, not aspirations. int8 value
quantization (codes + per-row scales, dequantized rerank) floors around
0.99 recall@10 on every realistic dataset we measured — the exact rerank
is exact w.r.t. the DEQUANTIZED rows, so quantization noise reaches the
final ordering and no overfetch can buy it back. The gates assert the
measured level with margin; DESIGN.md's compression-ladder table carries
the full bytes-vs-recall story per level.
"""

import dataclasses
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mpi_knn_tpu import KNNConfig, all_knn
from mpi_knn_tpu.ops.quant import (
    dequantize_rows,
    pack_int4,
    packed_dim,
    quantize_rows,
    row_wire_bytes,
    unpack_int4,
)
from tests.oracle import oracle_all_knn, recall_against_oracle

K = 10


def _mnist_like(rng, m=512, d=96):
    """Integer-pixel-magnitude data (the headline workload's regime) whose
    centered form is genuinely lossy under block-scaled int8."""
    return np.rint(rng.random((m, d)) * 255.0).astype(np.float32)


# ---------------------------------------------------------------------------
# primitive properties


@pytest.mark.parametrize("dtype", ["int8", "int4"])
@pytest.mark.parametrize("d", [32, 33])  # odd dim exercises the nibble pad
def test_roundtrip_error_within_half_scale(rng, dtype, d):
    x = (rng.standard_normal((64, d)) * rng.uniform(0.1, 200)).astype(
        np.float32
    )
    codes, scales = quantize_rows(jnp.asarray(x), dtype)
    assert codes.dtype == jnp.int8
    assert codes.shape == (64, packed_dim(d, dtype))
    back = np.asarray(dequantize_rows(codes, scales, dtype, d))
    err = np.abs(back - x)
    # round-to-nearest: every element within half a scale step (tiny fp
    # slack — the bound itself is computed in f32)
    assert (err <= np.asarray(scales)[:, None] / 2 + 1e-5).all()


@pytest.mark.parametrize("dtype", ["int8", "int4"])
def test_zero_block_and_all_negative_block(rng, dtype):
    x = np.zeros((3, 16), np.float32)
    x[1] = -np.abs(rng.standard_normal(16)).astype(np.float32) * 50 - 1.0
    x[2, 3] = 7.5  # one-hot-ish block: scale set by a single element
    codes, scales = quantize_rows(jnp.asarray(x), dtype)
    back = np.asarray(dequantize_rows(codes, scales, dtype, 16))
    # zero block: scale 0, codes 0, dequantization EXACTLY zero
    assert float(np.asarray(scales)[0]) == 0.0
    assert (back[0] == 0.0).all()
    # all-negative block: symmetric quantization is sign-faithful and the
    # extreme element reconstructs exactly (code = -qmax)
    assert (back[1] <= 0).all()
    amax_col = np.argmin(x[1])
    assert back[1, amax_col] == pytest.approx(x[1, amax_col], rel=1e-6)
    assert (np.abs(back[1] - x[1]) <= np.asarray(scales)[1] / 2 + 1e-5).all()


def test_nibble_pack_unpack_exact(rng):
    codes = rng.integers(-7, 8, size=(8, 31)).astype(np.int8)
    packed = pack_int4(jnp.asarray(codes))
    assert packed.shape == (8, 16)
    assert np.array_equal(np.asarray(unpack_int4(packed, 31)), codes)


def test_row_wire_bytes_ladder():
    # the single pricing rule: f32 4d, bf16 2d, int8 d+4, int4 d/2+4
    assert row_wire_bytes(128, None, 4) == 512
    assert row_wire_bytes(128, None, 2) == 256
    assert row_wire_bytes(128, "int8") == 132
    assert row_wire_bytes(128, "int4") == 68


# ---------------------------------------------------------------------------
# config validation (ISSUE 9 satellite: the message enumerates the
# accepted set; exact+int8 is refused loudly)


def test_transfer_dtype_message_enumerates_accepted_set():
    with pytest.raises(ValueError) as e:
        KNNConfig(ring_transfer_dtype="int16")
    for accepted in ("bfloat16", "float32", "int8"):
        assert accepted in str(e.value)


def test_int8_transfer_refused_under_exact_policy():
    with pytest.raises(ValueError, match="mixed"):
        KNNConfig(ring_transfer_dtype="int8", precision_policy="exact")


def test_quant_dtype_without_partitions_refused():
    with pytest.raises(ValueError, match="partitions"):
        KNNConfig(dtype="int8")


# ---------------------------------------------------------------------------
# transfer: the int8 ring


def test_ring_int8_recall_gate_uni_bidir_bit_identical(rng):
    """The transfer gate: block-scaled int8 rotation under the mixed
    pipeline holds recall@10 ≥ 0.99 vs the f64 oracle (measured ~0.993 on
    this data — the dequantized-rerank noise floor; bf16 sits at ~0.999,
    DESIGN.md carries the ladder), and the bidir schedule is
    BIT-IDENTICAL to uni: both dequantize the same codes, so the merge
    order cannot change a single bit."""
    X = _mnist_like(rng)
    want_d, want_i = oracle_all_knn(X, k=K)
    outs = {}
    for sched in ("uni", "bidir"):
        got = all_knn(
            X,
            k=K,
            backend="ring",
            precision_policy="mixed",
            ring_transfer_dtype="int8",
            ring_schedule=sched,
            query_tile=64,
            corpus_tile=128,
        )
        rec = recall_against_oracle(got.ids, want_d, want_i, K)
        assert rec >= 0.99, f"{sched}: recall@10 {rec} < 0.99"
        outs[sched] = got
    assert np.array_equal(outs["uni"].ids, outs["bidir"].ids)
    assert np.array_equal(outs["uni"].dists, outs["bidir"].dists)


@pytest.mark.parametrize("sched", ["uni", "bidir"])
def test_ring_int8_resumable_kill_resume_bit_identical(rng, sched, tmp_path):
    """The quantized travelers reconstruct across a kill: codes are a
    deterministic function of the f32 corpus, per-row quantization
    commutes with the resume roll, and the scale vectors thread through
    the one-round jits — so a killed-and-resumed run is bit-identical to
    an uninterrupted one on both schedules."""
    from mpi_knn_tpu.backends.ring_resumable import all_knn_ring_resumable

    X = _mnist_like(rng, m=300, d=48)
    qids = np.arange(300, dtype=np.int32)
    cfg = KNNConfig(
        k=8,
        backend="ring",
        precision_policy="mixed",
        ring_transfer_dtype="int8",
        ring_schedule=sched,
        query_tile=32,
        corpus_tile=64,
    )
    d_full, i_full = all_knn_ring_resumable(X, X, qids, cfg)
    ck = str(tmp_path / sched)
    all_knn_ring_resumable(
        X, X, qids, cfg, checkpoint_dir=ck, stop_after_rounds=2
    )
    d_res, i_res = all_knn_ring_resumable(X, X, qids, cfg, checkpoint_dir=ck)
    assert np.array_equal(np.asarray(i_full), np.asarray(i_res))
    assert np.array_equal(np.asarray(d_full), np.asarray(d_res))


def test_ring_int8_serve_parity_zero_compiles_and_gauge(rng):
    """Quantized serve cells ride the bucketed AOT cache: the resident
    index holds the WIRE representation (codes + scales, ~4× less HBM),
    serving is bit-identical to the one-shot driver, the steady state
    compiles nothing (jax.monitoring-counted), and the
    ``ring_transfer_wire_bytes`` gauge (stamped at lower time) shows the
    int8 rotation moving < 1/3 the bytes of the f32 rotation."""
    from mpi_knn_tpu.obs.metrics import get_registry, watch_compiles
    from mpi_knn_tpu.serve import ServeSession, build_index
    from mpi_knn_tpu.serve.engine import query_knn

    X = _mnist_like(rng)
    cfg = KNNConfig(
        k=K,
        backend="ring-overlap",
        precision_policy="mixed",
        ring_transfer_dtype="int8",
        query_tile=64,
        corpus_tile=128,
        query_bucket=64,
    )
    idx = build_index(X, cfg)
    assert idx.corpus_sharded.dtype == jnp.int8
    assert idx.corpus_scales_sharded is not None

    res = query_knn(X[:64], idx)
    got = all_knn(X, queries=X[:64], k=K, config=cfg)
    assert np.array_equal(res.ids, got.ids)
    np.testing.assert_allclose(res.dists, got.dists)

    session = ServeSession(idx)
    session.warm([64])
    session.submit(X[:64])
    session.drain()
    with watch_compiles() as compiles:
        for _ in range(3):
            session.submit(X[:64])
            session.drain()
    assert compiles == []

    gauges = get_registry().snapshot()["metrics"]
    int8_bytes = gauges["ring_transfer_wire_bytes"]["value"]
    idx_f32 = build_index(X, cfg.replace(ring_transfer_dtype=None))
    s2 = ServeSession(idx_f32)
    s2.warm([64])
    f32_bytes = get_registry().snapshot()["metrics"][
        "ring_transfer_wire_bytes"
    ]["value"]
    assert int8_bytes < f32_bytes / 3


def test_r4_permute_payload_at_most_27pct_of_f32(rng):
    """The ISSUE 9 wire acceptance, read from the LOWERED HLO at d=128:
    the int8 cell's total collective-permute payload bytes per rotation
    step are ≤ 0.27× the f32 cell's ((d + 4 + 4) / (4d + 4) = 0.264 at
    d=128 — codes + scale row + id row against f32 rows + id row)."""
    from mpi_knn_tpu.analysis.rules import count_collectives, max_buffer_bytes
    from mpi_knn_tpu.backends.ring import (
        _ring_knn_sharded,
        parse_ring_mesh,
        ring_tiles,
    )
    from mpi_knn_tpu.parallel.mesh import make_ring_mesh
    from mpi_knn_tpu.utils.hlo_graph import parse_hlo

    mesh = make_ring_mesh(None)
    q_axis, axis, dp, ring_n = parse_ring_mesh(mesh)
    d = 128
    m, nq = 256, 64

    def permute_bytes(cfg, corpus, scale):
        q_tile, c_tile, q_pad, c_pad = ring_tiles(cfg, m, nq, dp, ring_n)
        lowered = _ring_knn_sharded.lower(
            jnp.zeros((q_pad, d), jnp.float32),
            jnp.zeros((q_pad,), jnp.int32),
            corpus,
            jnp.zeros((c_pad,), jnp.int32),
            cfg,
            True,
            mesh,
            axis,
            q_tile,
            c_tile,
            q_axis=q_axis,
            corpus_scale=scale,
        )
        module = parse_hlo(lowered.compiler_ir("hlo").as_hlo_text())
        permutes = count_collectives(module).get("collective-permute", [])
        assert permutes, "no rotation permutes in the lowered ring"
        return sum(
            max_buffer_bytes(module.instr(c, n).type_str)
            for c, n in permutes
        )

    base = KNNConfig(k=K, backend="ring-overlap", precision_policy="mixed",
                     query_tile=32, corpus_tile=32)
    f32_cfg = base
    int8_cfg = base.replace(ring_transfer_dtype="int8")
    _, _, _, c_pad = ring_tiles(base, m, nq, dp, ring_n)
    f32_bytes = permute_bytes(
        f32_cfg, jnp.zeros((c_pad, d), jnp.float32), None
    )
    int8_bytes = permute_bytes(
        int8_cfg,
        jnp.zeros((c_pad, d), jnp.int8),
        jnp.zeros((c_pad,), jnp.float32),
    )
    assert int8_bytes <= 0.27 * f32_bytes, (int8_bytes, f32_bytes)


# ---------------------------------------------------------------------------
# at rest: int8/int4 clustered stores


def _brute_recall(X, ids, k):
    X64 = X.astype(np.float64)
    mu = X64.mean(0)
    Xc = X64 - mu
    D = (
        (Xc**2).sum(1)[:, None]
        + (Xc**2).sum(1)[None, :]
        - 2.0 * Xc @ Xc.T
    )[: ids.shape[0]]
    np.fill_diagonal(D[:, : ids.shape[0]], np.inf)
    want = np.argsort(D, 1, kind="stable")[:, :k]
    return np.mean(
        [len(set(a) & set(b)) / k for a, b in zip(ids, want)]
    )


@pytest.mark.parametrize("dtype,floor", [("int8", 0.95), ("int4", 0.70)])
def test_ivf_quantized_store_recall_floor(rng, dtype, floor):
    """Full-scan (nprobe == partitions) recall of the quantized store vs
    the f64 oracle — pure at-rest quantization loss, no partition
    pruning. Measured ~0.98 (int8) / ~0.86 (int4) on this data; bars
    carry margin."""
    from mpi_knn_tpu.ivf import build_ivf_index, search_ivf

    X = (rng.standard_normal((2048, 32)) * 3).astype(np.float32)
    idx = build_ivf_index(
        X, KNNConfig(k=K, partitions=16, nprobe=16, dtype=dtype)
    )
    _, ids = search_ivf(
        idx, X[:128], query_ids=np.arange(128, dtype=np.int32)
    )
    rec = _brute_recall(X, ids, K)
    assert rec >= floor, f"{dtype}: full-scan recall {rec} < {floor}"


@pytest.mark.parametrize("dtype", ["int8", "int4"])
def test_ivf_quantized_save_load_shard_roundtrips_bit_identical(
    rng, dtype, tmp_path
):
    from mpi_knn_tpu.ivf import (
        build_ivf_index,
        load_ivf_index,
        save_ivf_index,
        search_ivf,
        search_ivf_sharded,
        shard_ivf_index,
        unshard_ivf_index,
    )

    X = (rng.standard_normal((1024, 24)) * 3).astype(np.float32)
    idx = build_ivf_index(
        X, KNNConfig(k=5, partitions=8, nprobe=3, dtype=dtype)
    )
    d0, i0 = search_ivf(idx, X[:64])

    # save/load: codes, scales and results bit-identical
    path = save_ivf_index(idx, str(tmp_path / f"{dtype}.npz"))
    idx2 = load_ivf_index(path)
    assert np.array_equal(np.asarray(idx.buckets), np.asarray(idx2.buckets))
    assert np.array_equal(
        np.asarray(idx.bucket_scales), np.asarray(idx2.bucket_scales)
    )
    d1, i1 = search_ivf(idx2, X[:64])
    assert np.array_equal(i0, i1) and np.array_equal(d0, d1)

    # shard/unshard: layout derived, store bit-identical, search parity
    sidx = shard_ivf_index(idx2, shards=4)
    d2, i2, stats = search_ivf_sharded(sidx, X[:64])
    assert np.array_equal(i0, i2) and np.allclose(d0, d2)
    assert stats[:, 1].sum() == 0  # safe route cap: nothing dropped
    back = unshard_ivf_index(sidx)
    assert np.array_equal(np.asarray(back.buckets), np.asarray(idx.buckets))
    assert np.array_equal(
        np.asarray(back.bucket_scales), np.asarray(idx.bucket_scales)
    )


def test_ivf_quantized_serve_zero_compiles_and_at_rest_gauge(rng):
    from mpi_knn_tpu.ivf import build_ivf_index
    from mpi_knn_tpu.obs.metrics import get_registry, watch_compiles
    from mpi_knn_tpu.serve import ServeSession

    X = (rng.standard_normal((1024, 24)) * 3).astype(np.float32)
    idx = build_ivf_index(
        X,
        KNNConfig(k=5, partitions=8, nprobe=3, dtype="int8",
                  query_bucket=64),
    )
    session = ServeSession(idx)
    session.warm([64])
    session.submit(X[:64])
    session.drain()
    with watch_compiles() as compiles:
        for _ in range(3):
            session.submit(X[:64])
            session.drain()
    assert compiles == []
    gauge = get_registry().snapshot()["metrics"]["ivf_at_rest_bytes"]
    assert gauge["value"] == idx.nbytes_resident


def test_ivf_at_rest_byte_cuts_vs_same_layout_f32(rng):
    """The HBM claim, same bucket layout (padding cancels): int4 cuts
    ≥ 4× (measured ~7.5× at d=128: d/2 + 4 scale bytes vs 4d), int8
    ≥ 3× (~3.9×), bf16 exactly 2× on the row array."""
    from mpi_knn_tpu.ivf import build_ivf_index

    X = (rng.standard_normal((2048, 128)) * 3).astype(np.float32)
    sizes = {}
    for dtype in ("float32", "int8", "int4"):
        idx = build_ivf_index(
            X, KNNConfig(k=5, partitions=8, nprobe=2, dtype=dtype)
        )
        sizes[dtype] = idx.nbytes_resident
    assert sizes["float32"] >= 4 * sizes["int4"]
    assert sizes["float32"] >= 3 * sizes["int8"]


def test_sift32k_int4_acceptance_gate():
    """The ISSUE 9 int4 acceptance on the SIFT-shaped 32k gate, with the
    honestly MEASURED recall bar: the auto-tuned store reaches recall@10
    ≥ 0.80 vs the f64 oracle (measured ≈ 0.835 — int4 value quantization
    cannot reach the f32 index's 0.95-targeted level on this data; the
    ladder table in DESIGN.md documents the trade), the at-rest cut vs
    the same-layout f32 store is ≥ 4× (measured 7.5×), and R2-strict
    re-certifies the wire-priced probe-gather bound on the REAL lowered
    serve program (an f32-sized bucket gather — dequantizing before the
    gather — would fail the gate)."""
    from mpi_knn_tpu.analysis import engine
    from mpi_knn_tpu.analysis.lowering import (
        LintTarget,
        _ivf_meta,
        hlo_texts,
        serve_resident_bytes,
    )
    from mpi_knn_tpu.data.synthetic import make_sift_like
    from mpi_knn_tpu.ivf import build_ivf_index, search_ivf
    from mpi_knn_tpu.serve.engine import SCRATCH_PARAMS, lower_bucket

    X = make_sift_like(m=32768, d=128, seed=0)
    cfg = KNNConfig(k=K, partitions=64, kmeans_iters=10, query_bucket=256,
                    dtype="int4")
    idx = build_ivf_index(X, cfg)

    # measured recall@10 vs the f64 oracle on a held-out sample
    sample = np.linspace(0, 32767, num=128, dtype=np.int64)
    _, got = search_ivf(idx, X[sample], query_ids=sample.astype(np.int32))
    X64 = X.astype(np.float64)
    od = (
        (X64[sample] ** 2).sum(1)[:, None]
        + (X64**2).sum(1)[None, :]
        - 2.0 * (X64[sample] @ X64.T)
    )
    od[od <= 1e-9] = np.inf
    od[np.arange(len(sample)), sample] = np.inf
    order = np.argsort(od, axis=1, kind="stable")[:, : K + 5]
    want_d = np.take_along_axis(od, order, axis=1)
    rec = recall_against_oracle(got, want_d, order.astype(np.int32), K)
    assert rec >= 0.80, f"int4 32k gate: recall {rec} < 0.80"

    # ≥ 4× at-rest byte cut vs the same bucket layout at f32
    f32_layout_bytes = (
        idx.partitions * idx.bucket_cap * idx.dim * 4
    )
    assert f32_layout_bytes >= 4 * idx.nbytes_resident

    # R2-strict on the real serve-cache lowering, wire-priced gathers
    serve_cfg = idx.compatible_cfg(idx.cfg)
    lowered, q_pad, q_tile = lower_bucket(idx, serve_cfg, 256)
    target = LintTarget("ivf", "l2", "float32", serve=True, quant="int4")
    meta = {
        **_ivf_meta(idx, serve_cfg, q_tile, q_pad, 256),
        "serve": True,
        "donated_params": SCRATCH_PARAMS,
        # the f32-EQUIVALENT copy threshold: a quantized store's own
        # wire-width probe gather legitimately exceeds the compressed
        # residency (see lowering.serve_resident_bytes)
        "resident_bytes": serve_resident_bytes(idx),
    }
    assert meta["quantized"] is True
    ctx = engine.LintContext(target=target, cfg=serve_cfg, meta=meta)
    findings, ran = engine.run_rules(hlo_texts(lowered), ctx)
    assert {"R2-memory", "R3-dtype", "R6-ivf-probe", "R5-donation"} <= set(
        ran
    )
    assert not findings, "\n".join(
        f"[{f.rule}] {f.stage}: {f.message}" for f in findings
    )


def test_quantized_cfg_is_frozen_corpus_side(rng):
    """The at-rest dtype is baked into the store: a query config changing
    it is refused (serving int8 answers under an f32 label would lie
    about the math)."""
    from mpi_knn_tpu.ivf import build_ivf_index

    X = (rng.standard_normal((256, 16)) * 3).astype(np.float32)
    idx = build_ivf_index(
        X, KNNConfig(k=5, partitions=4, nprobe=2, dtype="int8")
    )
    with pytest.raises(ValueError, match="dtype"):
        idx.compatible_cfg(idx.cfg.replace(dtype="float32"))
