"""Fused collective-matmul ring rotation — CPU interpret-mode parity
certificate (DESIGN.md §3, the fused-rotation subsection).

``ring_fusion="fused"`` swaps the per-round XLA distance+merge body for
the fused Pallas kernel (``ops/pallas_ring.py``): tile distances, carry
merge and — on TPU's uni/exact round form — the next block's ICI
transfer all live in one kernel. Off-TPU the kernel runs in interpret
mode with the driver's ppermutes moving the identical wire bytes, which
is exactly what makes this matrix a real certificate: the fused COMPUTE
(the part that could silently diverge — masking, tie order, the k-merge,
dequantization) is proven bit-identical to the XLA form on every
schedule × policy × wire-format combination the config admits, so the
TPU form differs only in who issues the transfer.

Bit-identical means ``assert_array_equal`` on ids AND dists — not
allclose. The corpus has a planted duplicate row so tie-breaking and
zero-exclusion are exercised, and shard padding is exercised by P not
dividing anything special about m=96 at P=8 tiles.
"""

import numpy as np
import pytest

from mpi_knn_tpu import KNNConfig, all_knn
from mpi_knn_tpu.backends.ring import fused_blocking_undefined_error
from mpi_knn_tpu.backends.ring_resumable import all_knn_ring_resumable


def _corpus(m=96, d=12, seed=3):
    # small-integer grid values: exactly representable in bf16, so the
    # bfloat16 wire format changes no bits and the exact-policy × bf16
    # cell is a true bit-parity case (not an allclose compromise)
    rng = np.random.default_rng(seed)
    X = (rng.integers(0, 8, (m, d)) * 0.25).astype(np.float32)
    X[m // 6] = X[m // 2]  # planted duplicate → ties + zero-exclusion
    return X


def _ids(m):
    return np.arange(m, dtype=np.int32)


# every (policy, wire) combination the config admits: int8 requires the
# mixed policy (the rerank absorbs quantization — config.py refuses
# exact×int8), so the exact column carries None/bf16 only
_POLICY_WIRE = [
    ("exact", None),
    ("exact", "bfloat16"),
    ("mixed", None),
    ("mixed", "int8"),
]


@pytest.mark.parametrize("num_devices", [1, 2, 4, 8])
@pytest.mark.parametrize("schedule", ["uni", "bidir"])
@pytest.mark.parametrize("policy,wire", _POLICY_WIRE)
def test_fused_bit_identical_to_xla(num_devices, schedule, policy, wire):
    X = _corpus()
    kw = dict(
        k=5,
        backend="ring-overlap",
        num_devices=num_devices,
        query_tile=8,
        corpus_tile=16,
        ring_schedule=schedule,
        precision_policy=policy,
        ring_transfer_dtype=wire,
    )
    ref = all_knn(X, **kw, ring_fusion="xla")
    fus = all_knn(X, **kw, ring_fusion="fused")
    np.testing.assert_array_equal(
        np.asarray(ref.ids), np.asarray(fus.ids)
    )
    np.testing.assert_array_equal(
        np.asarray(ref.dists), np.asarray(fus.dists)
    )


def test_fused_resumable_kill_resume_bit_identical(rng, tmp_path):
    """Kill the fused rotation after 3 of 8 rounds, resume, and land
    bit-identical to an uninterrupted fused run AND to serial — the
    fused carry is the same (dists, ids) algebra the checkpoint already
    round-trips, so resume needs no new state."""
    X = _corpus()
    cfg = KNNConfig(
        k=5, query_tile=8, corpus_tile=16, ring_fusion="fused"
    )
    ck = tmp_path / "ck"
    rounds = []
    all_knn_ring_resumable(
        X, X, _ids(len(X)), cfg, checkpoint_dir=ck,
        stop_after_rounds=3, progress_cb=lambda r, t: rounds.append(r),
    )
    assert rounds == [1, 2, 3]

    rounds2 = []
    d, i = all_knn_ring_resumable(
        X, X, _ids(len(X)), cfg, checkpoint_dir=ck,
        progress_cb=lambda r, t: rounds2.append(r),
    )
    assert rounds2 == [4, 5, 6, 7, 8]  # resumed, not restarted

    d0, i0 = all_knn_ring_resumable(X, X, _ids(len(X)), cfg)
    np.testing.assert_array_equal(np.asarray(i0), np.asarray(i))
    np.testing.assert_array_equal(np.asarray(d0), np.asarray(d))
    # and the single-round fused driver keeps the parity claim: equal to
    # the xla resumable run bit for bit (serial would differ here only in
    # tie ORDER on the planted-duplicate corpus — ring vs serial merge
    # order, not a fused property; the matrix above owns that axis)
    dx, ix = all_knn_ring_resumable(
        X, X, _ids(len(X)), cfg.replace(ring_fusion="xla")
    )
    np.testing.assert_array_equal(np.asarray(ix), np.asarray(i))
    np.testing.assert_array_equal(np.asarray(dx), np.asarray(d))


def test_cross_fusion_resume_restarts(rng, tmp_path):
    """ring_fusion rides the checkpoint fingerprint: fused and xla
    carries are bit-identical BY TEST, not by contract — a fused run
    handed an xla checkpoint must RESTART (and still finish correctly)
    rather than adopt a carry from the other merge implementation."""
    X = _corpus(m=64)
    cfg = KNNConfig(k=3, query_tile=8, corpus_tile=16)
    ck = tmp_path / "ck"
    all_knn_ring_resumable(
        X, X, _ids(len(X)), cfg, checkpoint_dir=ck, stop_after_rounds=3
    )
    rounds = []
    d, i = all_knn_ring_resumable(
        X, X, _ids(len(X)), cfg.replace(ring_fusion="fused"),
        checkpoint_dir=ck, progress_cb=lambda r, t: rounds.append(r),
    )
    assert rounds[0] == 1  # restarted from round 0, not resumed
    dx, ix = all_knn_ring_resumable(X, X, _ids(len(X)), cfg)
    np.testing.assert_array_equal(np.asarray(ix), np.asarray(i))
    np.testing.assert_array_equal(np.asarray(dx), np.asarray(d))


def test_fused_refuses_blocking_schedule():
    """The fused form streams the next block DURING compute by
    construction — a 'blocking' fused run is a contradiction (TPU) or a
    silent mislabel (interpret), so backend='ring' refuses with the one
    shared wording."""
    X = _corpus(m=32)
    with pytest.raises(
        ValueError, match="undefined under the blocking schedule"
    ):
        all_knn(
            X, k=3, backend="ring", num_devices=2,
            query_tile=8, corpus_tile=16, ring_fusion="fused",
        )
    # the shared constructor and the raised error agree on the wording
    assert "undefined under the blocking schedule" in str(
        fused_blocking_undefined_error()
    )


def test_grid_rotation_refuses_resumable():
    """ring_fused_rotation='grid' is ONE kernel launch for the whole
    rotation — there is no per-round boundary for the resumable driver
    to checkpoint at, so single_round is refused loudly."""
    X = _corpus(m=32)
    cfg = KNNConfig(
        k=3, query_tile=8, corpus_tile=16,
        ring_fusion="fused", ring_fused_rotation="grid",
    )
    with pytest.raises(ValueError, match="no per-round boundary"):
        all_knn_ring_resumable(X, X, _ids(len(X)), cfg)


def test_grid_rotation_refuses_interpret_mode():
    """The whole-rotation grid form's between-round remote DMA cannot be
    emulated inside one interpret-mode evaluation — off-TPU it refuses
    and names the per-round form as the alternative."""
    X = _corpus(m=32)
    with pytest.raises(ValueError, match="cannot be emulated"):
        all_knn(
            X, k=3, backend="ring-overlap", num_devices=2,
            query_tile=8, corpus_tile=16,
            ring_fusion="fused", ring_fused_rotation="grid",
        )


def test_grid_rotation_config_refuses_int8_wire():
    """The grid form's float-wire contract is an EXPLICIT config rule,
    not a transitive accident of int8⇒mixed⇒not-grid: the kernel DMAs
    raw slot bytes and casts them into the dot, so int8 codes would skip
    dequantization. Pinned so relaxing either neighboring rule (int8's
    mixed requirement, grid's exact pin) can't silently admit it."""
    with pytest.raises(ValueError, match="float wire"):
        KNNConfig(
            k=3, query_tile=8, corpus_tile=16,
            ring_fusion="fused", ring_fused_rotation="grid",
            precision_policy="mixed", ring_transfer_dtype="int8",
        )


def test_grid_rotation_kernel_asserts_float_wire():
    """Defense in depth at the kernel boundary: fused_rotation_grid
    itself refuses a non-float block (before the TPU-only check, so the
    guard is testable off-TPU) — a future config relaxation could never
    stream quantized codes into the plain float cast."""
    import jax.numpy as jnp

    from mpi_knn_tpu.ops.pallas_ring import fused_rotation_grid

    cfg = KNNConfig(
        k=3, query_tile=8, corpus_tile=16,
        ring_fusion="fused", ring_fused_rotation="grid",
    )
    with pytest.raises(ValueError, match="float wire"):
        fused_rotation_grid(
            jnp.zeros((8, 4), jnp.float32),
            jnp.arange(8, dtype=jnp.int32),
            jnp.zeros((16, 4), jnp.int8),  # quantized codes: refused
            jnp.arange(16, dtype=jnp.int32),
            jnp.full((8, 3), jnp.inf, jnp.float32),
            jnp.full((8, 3), -1, jnp.int32),
            cfg=cfg, q_tile=8, c_tile=16, axis_name="ring", num_dev=2,
        )
