"""Ring backends vs serial backend on 8 virtual CPU devices — the
distributed-without-a-cluster strategy from SURVEY.md §4. Property: ring
output == serial output for any (m, k, P) — the property the reference's
buggy rotation violated (SURVEY.md Q1)."""

import jax
import numpy as np
import pytest

from mpi_knn_tpu import all_knn
from mpi_knn_tpu.parallel.mesh import make_ring_mesh


def _data(rng, m=96, d=12):
    return (rng.standard_normal((m, d)) * 3).astype(np.float32)


def _as_sets(ids):
    return [set(r.tolist()) for r in np.asarray(ids)]


@pytest.mark.parametrize("backend", ["ring", "ring-overlap"])
def test_ring_equals_serial_all_pairs(rng, backend):
    X = _data(rng, m=96)
    serial = all_knn(X, k=7, backend="serial", query_tile=32, corpus_tile=32)
    ring = all_knn(X, k=7, backend=backend)
    np.testing.assert_allclose(
        np.asarray(ring.dists), np.asarray(serial.dists), rtol=1e-5, atol=1e-5
    )
    assert _as_sets(ring.ids) == _as_sets(serial.ids)


@pytest.mark.parametrize("schedule", ["stream", "twolevel"])
def test_ring_merge_schedule_parity(rng, schedule):
    """The per-round block merge honors cfg.merge_schedule inside the ring
    (shared merge_tiles_into_carry) — both schedules must equal serial, with
    the block split across multiple on-device tiles so level 1 really runs
    per tile."""
    X = _data(rng, m=128)
    serial = all_knn(X, k=6, backend="serial", query_tile=32, corpus_tile=32)
    ring = all_knn(X, k=6, backend="ring", query_tile=8, corpus_tile=8,
                   merge_schedule=schedule)
    np.testing.assert_allclose(
        np.asarray(ring.dists), np.asarray(serial.dists), rtol=1e-5, atol=1e-5
    )
    assert _as_sets(ring.ids) == _as_sets(serial.ids)


def test_ring_bf16_transfer_exact_on_integer_data(rng):
    """ring_transfer_dtype='bfloat16' halves the bytes per ppermute; on
    integer-valued data (raw pixels <= 255 are bf16-exact) the results must
    equal serial EXACTLY. center off so values stay integral."""
    X = np.rint(rng.random((96, 24)) * 255.0).astype(np.float32)
    serial = all_knn(X, k=5, backend="serial", center=False, zero_eps=0.5,
                     query_tile=32, corpus_tile=32)
    ring = all_knn(X, k=5, backend="ring", center=False, zero_eps=0.5,
                   ring_transfer_dtype="bfloat16")
    np.testing.assert_allclose(
        np.asarray(ring.dists), np.asarray(serial.dists), rtol=1e-6
    )
    assert _as_sets(ring.ids) == _as_sets(serial.ids)


def test_ring_bf16_transfer_recall_on_float_data(rng):
    """On non-integer data the one-time bf16 cast of the rotating block may
    flip near-ties; id-set recall vs serial is the contract (>= 0.99 on
    well-separated blobs)."""
    from mpi_knn_tpu.utils.report import recall_at_k

    X = _data(rng, m=128)
    serial = all_knn(X, k=6, backend="serial", query_tile=32, corpus_tile=32)
    ring = all_knn(X, k=6, backend="ring-overlap",
                   ring_transfer_dtype="bfloat16")
    rec = recall_at_k(np.asarray(ring.ids), np.asarray(serial.ids))
    assert rec >= 0.99, rec


@pytest.mark.parametrize("backend", ["ring", "ring-overlap"])
def test_ring_non_divisible_m(rng, backend):
    """m=101 is not divisible by P=8 — the reference silently corrupted here
    (SURVEY.md Q6); we pad and mask."""
    X = _data(rng, m=101)
    serial = all_knn(X, k=5, backend="serial", query_tile=32, corpus_tile=32)
    ring = all_knn(X, k=5, backend=backend)
    np.testing.assert_allclose(
        np.asarray(ring.dists), np.asarray(serial.dists), rtol=1e-5, atol=1e-5
    )
    assert _as_sets(ring.ids) == _as_sets(serial.ids)


def test_ring_query_mode(rng):
    X = _data(rng, m=80)
    Q = _data(rng, m=37)
    serial = all_knn(X, queries=Q, k=6, backend="serial", query_tile=16, corpus_tile=16)
    ring = all_knn(X, queries=Q, k=6, backend="ring-overlap")
    np.testing.assert_allclose(
        np.asarray(ring.dists), np.asarray(serial.dists), rtol=1e-5, atol=1e-5
    )
    assert _as_sets(ring.ids) == _as_sets(serial.ids)


def test_ring_cosine(rng):
    X = _data(rng, m=64)
    serial = all_knn(X, k=4, backend="serial", metric="cosine", query_tile=16, corpus_tile=16)
    ring = all_knn(X, k=4, backend="ring", metric="cosine")
    np.testing.assert_allclose(
        np.asarray(ring.dists), np.asarray(serial.dists), rtol=1e-5, atol=1e-5
    )


def test_ring_explicit_small_mesh(rng):
    """Ring over a 4-device sub-mesh via explicit mesh argument."""
    X = _data(rng, m=64)
    mesh = make_ring_mesh(4)
    serial = all_knn(X, k=5, backend="serial", query_tile=16, corpus_tile=16)
    ring = all_knn(X, k=5, backend="ring-overlap", mesh=mesh)
    np.testing.assert_allclose(
        np.asarray(ring.dists), np.asarray(serial.dists), rtol=1e-5, atol=1e-5
    )


def test_ring_k_spans_blocks(rng):
    """k larger than any single shard (12 per device at m=96/P=8) forces the
    cross-round merge to actually carry state between rotations."""
    X = _data(rng, m=96)
    serial = all_knn(X, k=20, backend="serial", query_tile=32, corpus_tile=32)
    ring = all_knn(X, k=20, backend="ring-overlap")
    np.testing.assert_allclose(
        np.asarray(ring.dists), np.asarray(serial.dists), rtol=1e-5, atol=1e-5
    )
    assert _as_sets(ring.ids) == _as_sets(serial.ids)


def test_auto_backend_resolves_on_multi_device():
    """The package docstring's own example must work on a multi-device host
    (auto -> ring-overlap)."""
    X = np.random.default_rng(3).standard_normal((40, 8)).astype(np.float32)
    res = all_knn(X, k=3)
    assert res.ids.shape == (40, 3)


def test_output_sharding_follows_ring(rng):
    """The result must stay sharded over the ring axis (no hidden all-gather
    inside the backend) — device memory for the output scales as q/P."""
    from jax.sharding import PartitionSpec

    X = _data(rng, m=96)
    ring = all_knn(X, k=4, backend="ring-overlap")
    assert len(jax.devices()) == 8
    assert ring.dists.shape == (96, 4)
    spec = ring.dists.sharding.spec
    assert spec[0] == "ring", f"expected query axis sharded over ring, got {spec}"


@pytest.mark.parametrize("p", [1, 2, 3, 4, 8])
@pytest.mark.parametrize("backend", ["ring", "ring-overlap"])
def test_bidir_bit_identical_to_serial_every_p(rng, p, backend):
    """The tentpole property of the full-duplex schedule: at EVERY ring
    size — including the degenerate P=1, the all-rounds-degenerate P=2, an
    odd P, and even Ps with a real antipodal round — bidir results are
    bit-identical to serial AND to the uni ring (tiles pinned equal on both
    sides so the per-pair distance kernels match shape-for-shape)."""
    X = _data(rng, m=96)
    mesh = make_ring_mesh(p)
    serial = all_knn(X, k=7, backend="serial", query_tile=4, corpus_tile=4)
    uni = all_knn(X, k=7, backend=backend, mesh=mesh,
                  query_tile=4, corpus_tile=4)
    bidir = all_knn(X, k=7, backend=backend, mesh=mesh,
                    query_tile=4, corpus_tile=4, ring_schedule="bidir")
    np.testing.assert_array_equal(
        np.asarray(bidir.ids), np.asarray(serial.ids)
    )
    np.testing.assert_array_equal(
        np.asarray(bidir.dists), np.asarray(serial.dists)
    )
    np.testing.assert_array_equal(
        np.asarray(bidir.dists), np.asarray(uni.dists)
    )


@pytest.mark.parametrize("p", [1, 2, 3, 4, 8])
def test_bidir_mixed_precision_bit_identical_every_p(rng, p):
    """bidir × precision_policy='mixed': the compress-and-rerank pipeline
    lives inside the shared per-tile reduction, so the schedule change must
    not perturb it — bit-identity to the mixed serial backend at every P
    (c_tile=16 > 4k=12 so the two-pass pipeline actually runs)."""
    X = _data(rng, m=128, d=16)
    cfg_kw = dict(k=3, query_tile=8, corpus_tile=16,
                  precision_policy="mixed")
    serial = all_knn(X, backend="serial", **cfg_kw)
    bidir = all_knn(X, backend="ring-overlap", mesh=make_ring_mesh(p),
                    ring_schedule="bidir", **cfg_kw)
    np.testing.assert_array_equal(
        np.asarray(bidir.ids), np.asarray(serial.ids)
    )
    np.testing.assert_array_equal(
        np.asarray(bidir.dists), np.asarray(serial.dists)
    )


def test_bidir_bf16_transfer_exact_on_integer_data(rng):
    """ring_transfer_dtype composes with bidir: BOTH travelers circulate at
    the transfer dtype (cast once, upcast per merge), so integer-valued
    data stays exactly equal to serial."""
    X = np.rint(rng.random((96, 24)) * 255.0).astype(np.float32)
    serial = all_knn(X, k=5, backend="serial", center=False, zero_eps=0.5,
                     query_tile=32, corpus_tile=32)
    ring = all_knn(X, k=5, backend="ring", center=False, zero_eps=0.5,
                   ring_transfer_dtype="bfloat16", ring_schedule="bidir")
    np.testing.assert_allclose(
        np.asarray(ring.dists), np.asarray(serial.dists), rtol=1e-6
    )
    assert _as_sets(ring.ids) == _as_sets(serial.ids)


def test_bidir_non_divisible_m(rng):
    """Padding + masking under the two-traveler rotation (m=101, P=8)."""
    X = _data(rng, m=101)
    serial = all_knn(X, k=5, backend="serial", query_tile=32, corpus_tile=32)
    ring = all_knn(X, k=5, backend="ring-overlap", ring_schedule="bidir")
    np.testing.assert_allclose(
        np.asarray(ring.dists), np.asarray(serial.dists), rtol=1e-5, atol=1e-5
    )
    assert _as_sets(ring.ids) == _as_sets(serial.ids)


def test_bidir_query_mode(rng):
    X = _data(rng, m=80)
    Q = _data(rng, m=37)
    serial = all_knn(X, queries=Q, k=6, backend="serial",
                     query_tile=16, corpus_tile=16)
    ring = all_knn(X, queries=Q, k=6, backend="ring-overlap",
                   ring_schedule="bidir")
    np.testing.assert_allclose(
        np.asarray(ring.dists), np.asarray(serial.dists), rtol=1e-5, atol=1e-5
    )
    assert _as_sets(ring.ids) == _as_sets(serial.ids)


def test_ring_respects_tiling(rng):
    """Tiny tiles force the per-device nested tiling path; results unchanged."""
    X = _data(rng, m=96)
    serial = all_knn(X, k=5, backend="serial", query_tile=16, corpus_tile=16)
    ring = all_knn(X, k=5, backend="ring-overlap", query_tile=4, corpus_tile=4)
    np.testing.assert_allclose(
        np.asarray(ring.dists), np.asarray(serial.dists), rtol=1e-5, atol=1e-5
    )
    assert _as_sets(ring.ids) == _as_sets(serial.ids)
