"""Peak-HBM certification (ISSUE 15): the liveness analyzer, the R7
rule, the per-cell memory ledger, and the regression gate.

Four layers:

- ANALYZER unit tests on hand-written HLO: def-use interval peaks,
  forwarding ops allocate nothing, while bodies are loop-resident,
  conditional branches max (not sum), aliased donated outputs count
  once, the tuple pointer table matches PJRT's accounting;
- INJECTED counterexamples through the production rule path
  (``engine.run_rules`` — the test_hlo_lint convention): an un-donated
  scratch that doubles residency, a corpus-sized temp that hides under
  R2's largest-input per-buffer floor (the R2-audit latent hole, pinned
  as caught-by-R7), and a PJRT disagreement;
- the LEDGER: round trip, tolerance-gate pass/fail in both directions
  (growth = regression, shrinkage = stale), new-cell-extends vs
  vanished-cell-is-a-finding semantics, and drift through the
  production ``mpi-knn lint --memory --ledger-check`` CLI;
- the SERVING surface: the ``serve_peak_hbm_bytes`` gauge stamped at
  build time, the session snapshot, and the doctor's memory block.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mpi_knn_tpu.analysis import engine, lowering, memory
from mpi_knn_tpu.analysis import rules as rules_mod
from mpi_knn_tpu.config import KNNConfig


def _ctx(backend="serial", metric="l2", dtype="float32", serve=False,
         **meta):
    meta.setdefault("q_tile", 8)
    meta.setdefault("c_tile", 16)
    meta.setdefault("acc_bytes", 4)
    return engine.LintContext(
        target=lowering.LintTarget(backend, metric, dtype, serve=serve),
        cfg=KNNConfig(k=4, metric=metric, query_tile=8, corpus_tile=16),
        meta=meta,
    )


def _rules(*names):
    return [r for r in rules_mod.RULES if r.name in names]


# ---------------------------------------------------------------------------
# the analyzer on hand-written modules (full control over the shapes —
# no XLA whims between the test and the property)

_LINEAR = """\
HloModule m, entry_computation_layout={(f32[64,32]{1,0})->f32[64,32]{1,0}}

ENTRY %main.1 (a.1: f32[64,32]) -> f32[64,32] {
  %a.1 = f32[64,32]{1,0} parameter(0)
  %b.1 = f32[64,32]{1,0} add(%a.1, %a.1)
  %c.1 = f32[64,32]{1,0} multiply(%b.1, %b.1)
  ROOT %d.1 = f32[64,32]{1,0} negate(%c.1)
}
"""


def test_analyzer_linear_chain_intervals():
    """b dies when c is defined, c when d is: at most two of the three
    8 KiB temporaries are ever live, and the root buffer is the output
    (not a temp)."""
    a = memory.analyze_module(_LINEAR)
    buf = 64 * 32 * 4
    assert a.args_bytes == buf
    assert a.output_bytes == buf
    assert a.aliased_bytes == 0
    # live at the peak: b + c (d IS the output and is excluded from the
    # temp sweep's largest tracking but still occupies output bytes)
    assert a.temp_peak_bytes == 2 * buf
    assert a.peak_bytes == buf + buf + 2 * buf


def test_analyzer_forwarding_is_free():
    """tuple / gte / bitcast shuffle pointers — zero new bytes."""
    mod = """\
HloModule m, entry_computation_layout={(f32[64,32]{1,0})->f32[64,32]{1,0}}

ENTRY %main.1 (a.1: f32[64,32]) -> f32[64,32] {
  %a.1 = f32[64,32]{1,0} parameter(0)
  %t.1 = (f32[64,32]{1,0}, f32[64,32]{1,0}) tuple(%a.1, %a.1)
  %g.1 = f32[64,32]{1,0} get-tuple-element(%t.1), index=0
  ROOT %b.1 = f32[64,32]{1,0} bitcast(%g.1)
}
"""
    a = memory.analyze_module(mod)
    assert a.temp_peak_bytes == 0
    # the output is the forwarded parameter — no new output allocation
    # is modeled, but output_bytes still reports the declared result
    assert a.output_bytes == 64 * 32 * 4


def test_analyzer_aliased_output_counts_once():
    """The same store-update program, donated vs not: the aliased form's
    peak is one store smaller — the donated scratch counts once."""
    body = """\

ENTRY %main.1 (u.1: f32[32,32], s.1: f32[1024,32]) -> f32[1024,32] {
  %u.1 = f32[32,32]{1,0} parameter(0)
  %s.1 = f32[1024,32]{1,0} parameter(1)
  ROOT %n.1 = f32[1024,32]{1,0} negate(%s.1)
}
"""
    layout = ("entry_computation_layout={(f32[32,32]{1,0}, "
              "f32[1024,32]{1,0})->f32[1024,32]{1,0}}")
    donated = memory.analyze_module(
        "HloModule m, input_output_alias={ {}: (1, {}, may-alias) }, "
        + layout + body
    )
    undonated = memory.analyze_module("HloModule m, " + layout + body)
    store = 1024 * 32 * 4
    assert donated.aliased_bytes == store
    assert undonated.aliased_bytes == 0
    assert undonated.peak_bytes - donated.peak_bytes == store


def test_analyzer_while_body_is_loop_resident():
    """The loop body's internal scratch rides on top of the caller's
    live set while the while executes."""
    mod = """\
HloModule m, entry_computation_layout={(f32[64,32]{1,0})->f32[64,32]{1,0}}

%body.1 (p.1: f32[64,32]) -> f32[64,32] {
  %p.1 = f32[64,32]{1,0} parameter(0)
  %big.1 = f32[512,32]{1,0} broadcast(%p.1), dimensions={0,1}
  %sl.1 = f32[64,32]{1,0} slice(%big.1), slice={[0:64], [0:32]}
  ROOT %r.1 = f32[64,32]{1,0} add(%p.1, %sl.1)
}

%cond.1 (q.1: f32[64,32]) -> pred[] {
  %q.1 = f32[64,32]{1,0} parameter(0)
  ROOT %lt.1 = pred[] constant(false)
}

ENTRY %main.1 (a.1: f32[64,32]) -> f32[64,32] {
  %a.1 = f32[64,32]{1,0} parameter(0)
  %c.1 = f32[64,32]{1,0} copy(%a.1)
  ROOT %w.1 = f32[64,32]{1,0} while(%c.1), condition=%cond.1, body=%body.1
}
"""
    a = memory.analyze_module(mod)
    # at the while: the state (the copy, 8K) + the body's broadcast
    # (64K) + the body's add result (8K) are live together
    assert a.temp_peak_bytes >= 64 * 32 * 4 + 512 * 32 * 4
    assert a.largest_temp_op == "broadcast"


def test_analyzer_conditional_branches_max_not_sum():
    mod_tmpl = """\
HloModule m, entry_computation_layout={(pred[], f32[8,8]{1,0})->f32[8,8]{1,0}}

%true.1 (p.1: f32[8,8]) -> f32[8,8] {
  %p.1 = f32[8,8]{1,0} parameter(0)
  %b.1 = f32[BIG,8]{1,0} broadcast(%p.1), dimensions={0,1}
  %s.1 = f32[8,8]{1,0} slice(%b.1), slice={[0:8], [0:8]}
  ROOT %r.1 = f32[8,8]{1,0} add(%p.1, %s.1)
}

%false.1 (q.1: f32[8,8]) -> f32[8,8] {
  %q.1 = f32[8,8]{1,0} parameter(0)
  %b.2 = f32[BIG,8]{1,0} broadcast(%q.1), dimensions={0,1}
  %s.2 = f32[8,8]{1,0} slice(%b.2), slice={[0:8], [0:8]}
  ROOT %r.2 = f32[8,8]{1,0} add(%q.1, %s.2)
}

ENTRY %main.1 (p.0: pred[], a.1: f32[8,8]) -> f32[8,8] {
  %p.0 = pred[] parameter(0)
  %a.1 = f32[8,8]{1,0} parameter(1)
  ROOT %c.1 = f32[8,8]{1,0} conditional(%p.0, %a.1, %a.1), true_computation=%true.1, false_computation=%false.1
}
"""
    a = memory.analyze_module(mod_tmpl.replace("BIG", "256"))
    # one branch's broadcast (256·8·4 = 8192), never both at once
    assert a.temp_peak_bytes < 2 * 256 * 8 * 4
    assert a.temp_peak_bytes >= 256 * 8 * 4


def test_analyzer_matches_pjrt_on_a_real_program():
    """The honesty anchor as a unit test: structural components match
    PJRT exactly, the total peak sits inside the declared band."""
    lowered = jax.jit(lambda a: (a @ a.T).sum(axis=0)).lower(
        jnp.zeros((64, 32), jnp.float32)
    )
    compiled = lowered.compile()
    pjrt = memory.pjrt_memory_stats(compiled)
    assert pjrt is not None
    a = memory.analyze_module(compiled.as_text())
    assert memory.crosscheck_pjrt(a, pjrt) == []
    assert a.args_bytes == pjrt["argument_bytes"]
    assert a.output_bytes == pjrt["output_bytes"]


def test_crosscheck_flags_structural_and_band_disagreement():
    a = memory.analyze_module(_LINEAR)
    good = {
        "argument_bytes": a.args_bytes,
        "output_bytes": a.output_bytes,
        "alias_bytes": a.aliased_bytes,
        "temp_bytes": a.temp_peak_bytes,
        "peak_bytes": a.peak_bytes,
    }
    assert memory.crosscheck_pjrt(a, good) == []
    bad_struct = dict(good, argument_bytes=a.args_bytes + 4)
    assert any("argument" in w for w in memory.crosscheck_pjrt(a, bad_struct))
    # a peak disagreement far past the band (analyzer would be missing
    # a corpus-sized buffer): must be loud
    bad_peak = dict(good, peak_bytes=a.peak_bytes * 10 + 10 ** 6)
    assert any("beyond tolerance" in w
               for w in memory.crosscheck_pjrt(a, bad_peak))


# ---------------------------------------------------------------------------
# injected counterexamples through the PRODUCTION rule path


def test_counterexample_undonated_scratch_doubles_residency():
    """The same in-place store update lowered WITHOUT donation: the
    output no longer aliases the donated store, residency doubles, and
    R7's budget (which grants donated cells NO unaliased-output
    allowance) must fire — while the donated production shape is clean
    under the identical context."""
    store = jnp.zeros((8192, 32), jnp.float32)
    rows = jnp.zeros((32, 32), jnp.float32)

    def update(rows, store):
        return store.at[:32].set(rows)

    meta = dict(
        q_tile=32, c_tile=32, acc_bytes=4,
        donated_params=(1,), budget_elems=32 * 32,
    )
    ctx = _ctx(serve=True, **meta)
    undonated = lowering.hlo_texts(jax.jit(update).lower(rows, store))
    findings, ran = engine.run_rules(
        undonated, ctx, _rules("R7-peak-memory")
    )
    assert ran == ["R7-peak-memory"]
    assert any("peak live bytes" in f.message for f in findings), [
        f.message for f in findings
    ]
    # the finding names its numbers: peak ≈ 2× the donated peak
    f = next(f for f in findings if "peak live bytes" in f.message)
    assert f.details["peak_bytes"] > 2 * 8192 * 32 * 4

    donated = lowering.hlo_texts(
        jax.jit(update, donate_argnums=(1,)).lower(rows, store)
    )
    ok_findings, _ = engine.run_rules(
        donated, _ctx(serve=True, **meta), _rules("R7-peak-memory")
    )
    assert not ok_findings, [f.message for f in ok_findings]


def test_counterexample_corpus_temp_under_r2_radar():
    """A corpus-sized intermediate whose largest single buffer equals
    the largest input: R2's per-buffer floor passes it (the latent hole
    the ISSUE 15 audit names), R7's liveness peak — whose temp budget
    deliberately has NO input floor — fires and names the culprit."""

    def sneaky(q, c):
        c2 = jnp.cumsum(c, axis=0)  # corpus-sized live intermediates
        return q[:8] @ c2[:16].T  # tiny output

    lowered = jax.jit(sneaky).lower(
        jnp.zeros((64, 32), jnp.float32),
        jnp.zeros((4096, 32), jnp.float32),
    )
    texts = lowering.hlo_texts(lowered)
    ctx = _ctx()
    r2_findings, _ = engine.run_rules(texts, ctx, _rules("R2-memory"))
    assert not r2_findings, [f.message for f in r2_findings]
    r7_findings, _ = engine.run_rules(texts, _ctx(),
                                      _rules("R7-peak-memory"))
    over = [f for f in r7_findings if "peak live bytes" in f.message]
    assert over, "corpus-sized temp passed the liveness budget"
    # the report names a culprit an operator can grep for
    assert over[0].details["largest_temp"]["bytes"] >= 4096 * 32 * 4 / 2


def test_counterexample_pjrt_disagreement_is_a_finding():
    """Feed R7 a doctored PJRT report (as if the runtime saw half the
    memory the analyzer sees): the cross-check must fire through the
    production rule path."""
    texts, cfg, meta = lowering.lower_target(
        lowering.LintTarget("serial", "l2", "float32")
    )
    bad_meta = dict(meta)
    real = bad_meta.get("pjrt_memory")
    assert real is not None, "lowering no longer captures PJRT stats"
    bad_meta["pjrt_memory"] = {
        **real, "peak_bytes": max(1, real["peak_bytes"] // 10),
    }
    ctx = engine.LintContext(
        target=lowering.LintTarget("serial", "l2", "float32"),
        cfg=cfg, meta=bad_meta,
    )
    findings, _ = engine.run_rules(texts, ctx, _rules("R7-peak-memory"))
    assert any("beyond tolerance" in f.message for f in findings)
    # and with the REAL numbers the same cell is clean
    ctx2 = engine.LintContext(
        target=lowering.LintTarget("serial", "l2", "float32"),
        cfg=cfg, meta=dict(meta),
    )
    ok, _ = engine.run_rules(texts, ctx2, _rules("R7-peak-memory"))
    assert not ok, [f.message for f in ok]


# ---------------------------------------------------------------------------
# the R2-floor audit (ISSUE 15 satellite): every divergence between
# R2's input-floored per-buffer budget and R7's floor-free temp budget
# is either absorbed by the derived allowance or carried by a NAMED
# registered allowance — no cell silently leans on the input floor


def _default_meta(target):
    try:
        _, _, meta = lowering.lower_target(target)
    except lowering.UnsupportedTarget:
        return None
    return meta


def test_r2_floor_audit_allowances_are_named_and_load_bearing():
    allowed = []
    for t in lowering.default_targets():
        meta = _default_meta(t)
        if meta is None:
            continue
        if meta.get("peak_extra_elems"):
            allowed.append(t)
    # exactly the two audited divergences: the bf16 store's f32 upcast
    # (dense serial cells) and the pallas mixed survivor restack — a new
    # entry here means a new divergence that needs a rationale in
    # analysis/lowering.py AND this pin extended
    families = {
        (t.backend, t.dtype, t.policy) for t in allowed
    }
    assert families == {
        ("serial", "bfloat16", "exact"),
        ("pallas", "float32", "mixed"),
    }, families
    # and each allowance is load-bearing: dropping it fires R7 (the
    # audit found a real divergence, not a cargo-cult slack bump)
    for t in (
        lowering.LintTarget("serial", "l2", "bfloat16"),
        lowering.LintTarget("pallas", "l2", "float32", "mixed"),
    ):
        texts, cfg, meta = lowering.lower_target(t)
        stripped = dict(meta)
        stripped.pop("peak_extra_elems")
        ctx = engine.LintContext(target=t, cfg=cfg, meta=stripped)
        findings, _ = engine.run_rules(texts, ctx,
                                       _rules("R7-peak-memory"))
        assert any("peak live bytes" in f.message for f in findings), (
            t.label, "allowance is not load-bearing — remove it"
        )


# ---------------------------------------------------------------------------
# the ledger


def _cell(peak, budget=None):
    return {
        "args_bytes": peak // 2, "output_bytes": 64, "aliased_bytes": 0,
        "temp_peak_bytes": peak // 2, "peak_bytes": peak,
        "largest_temp": {"bytes": peak // 4, "op": "dot",
                         "instruction": "main::d.1"},
        "peak_at": "d.1",
        "categories": {"scratch": 0, "temp": peak // 2, "exchange": 0},
        "budget_bytes": budget if budget is not None else peak * 2,
        "pjrt": None,
    }


def test_ledger_round_trip_and_merge(tmp_path):
    path = tmp_path / "memory_ledger.json"
    assert memory.load_ledger(path) is None
    doc = memory.save_ledger(path, {"a/l2/f32": _cell(1000)})
    loaded = memory.load_ledger(path)
    assert loaded["cells"] == doc["cells"]
    assert loaded["schema_version"] == memory.LEDGER_SCHEMA_VERSION
    assert loaded["tolerance"] == {
        "rel": memory.LEDGER_TOL_REL, "abs_bytes": memory.LEDGER_TOL_ABS,
    }
    # a filtered refresh merges: the un-re-lowered cell survives
    memory.save_ledger(path, {"b/l2/f32": _cell(2000)}, merge_into=loaded)
    merged = memory.load_ledger(path)
    assert set(merged["cells"]) == {"a/l2/f32", "b/l2/f32"}
    # unknown schema is refused loudly, not silently re-interpreted
    path.write_text(json.dumps({"schema_version": 99, "cells": {}}))
    with pytest.raises(ValueError):
        memory.load_ledger(path)


def test_ledger_tolerance_gate_both_directions(tmp_path):
    committed = memory.save_ledger(
        tmp_path / "l.json", {"cell": _cell(100_000)}
    )
    # inside tolerance: green both ways
    assert memory.ledger_drift(
        committed, {"cell": _cell(100_000 + 2000)}, full_matrix=True
    ) == []
    assert memory.ledger_drift(
        committed, {"cell": _cell(100_000 - 2000)}, full_matrix=True
    ) == []
    # growth beyond tolerance: a regression, naming the culprit
    grew = memory.ledger_drift(
        committed, {"cell": _cell(200_000)}, full_matrix=True
    )
    assert grew and "grew" in grew[0] and "dot" in grew[0]
    # shrinkage beyond tolerance: a stale ledger
    shrank = memory.ledger_drift(
        committed, {"cell": _cell(50_000)}, full_matrix=True
    )
    assert shrank and "shrank" in shrank[0]


def test_ledger_new_cell_extends_vanished_cell_fires(tmp_path):
    committed = memory.save_ledger(
        tmp_path / "l.json", {"old": _cell(1000)}
    )
    # a NEW cell extends the ledger silently
    assert memory.ledger_drift(
        committed, {"old": _cell(1000), "new": _cell(5000)},
        full_matrix=True,
    ) == []
    # a VANISHED cell is a finding on full-matrix runs only (a filtered
    # sweep legitimately re-lowers a subset)
    gone_full = memory.ledger_drift(committed, {}, full_matrix=True)
    assert gone_full and "vanished" in gone_full[0]
    assert memory.ledger_drift(committed, {}, full_matrix=False) == []
    # an ENVIRONMENT-SKIPPED cell (a too-small mesh) is a coverage gap,
    # not a vanished certification — `--devices 1` must not fail every
    # committed ring cell
    assert memory.ledger_drift(
        committed, {}, full_matrix=True, skipped_labels={"old"}
    ) == []


def test_ledger_full_regeneration_purges_vanished_cells(tmp_path):
    """The drift error's prescribed remedy must actually work: after a
    cell is removed from the matrix on purpose, a full-matrix
    `--memory` regeneration drops its committed entry (merge_base_for
    returns no merge base) instead of re-importing it forever — while
    an environment-skipped cell keeps its entry, and a FILTERED sweep
    still preserves the whole committed ledger."""
    committed = memory.save_ledger(
        tmp_path / "l.json",
        {"removed": _cell(1000), "skipped": _cell(2000),
         "kept": _cell(3000)},
    )
    # full regeneration, nothing skipped: no merge base → vanished
    # cells purge
    assert memory.merge_base_for(committed, full_matrix=True) is None
    # full regeneration with an env-skip: only the skipped cell's
    # committed entry survives the merge
    base = memory.merge_base_for(
        committed, full_matrix=True, skipped_labels={"skipped"}
    )
    assert set(base["cells"]) == {"skipped"}
    doc = memory.save_ledger(
        tmp_path / "l.json", {"kept": _cell(3000)}, merge_into=base
    )
    assert set(doc["cells"]) == {"kept", "skipped"}
    # filtered sweep: the committed ledger is preserved wholesale
    assert memory.merge_base_for(
        committed, full_matrix=False
    ) is committed
    assert memory.merge_base_for(None, full_matrix=True) is None


def test_ledger_drift_through_production_cli(tmp_path):
    """The ledger-drift counterexample through the REAL `mpi-knn lint
    --memory --ledger-check` path: a committed ledger whose serial cell
    claims half the real peak must fail the gate (exit 1), and the
    freshly-written ledger must pass it (exit 0)."""
    from mpi_knn_tpu.analysis import cli as lint_cli

    args = ["--backend", "serial", "--metric", "l2", "--dtype", "float32",
            "--policy", "exact", "--schedule", "uni",
            "--out", str(tmp_path), "-q"]
    # generate the honest ledger for the one-cell sweep
    rc = lint_cli.main(args + ["--memory"])
    assert rc == 0
    ledger_path = tmp_path / "memory_ledger.json"
    honest = json.loads(ledger_path.read_text())
    label = "serial/l2/float32"
    assert label in honest["cells"]
    # the honest ledger passes the check
    assert lint_cli.main(args + ["--memory", "--ledger-check"]) == 0
    # tamper: halve the committed peak — the real program now "grew"
    honest["cells"][label]["peak_bytes"] //= 2
    ledger_path.write_text(json.dumps(honest))
    assert lint_cli.main(args + ["--memory", "--ledger-check"]) == 1
    # usage errors stay loud: --ledger-check without --memory, and a
    # --rule filter that would sweep WITHOUT R7
    assert lint_cli.main(args + ["--ledger-check"]) == 2
    assert lint_cli.main(
        args + ["--memory", "--rule", "R2-memory"]
    ) == 2
    # missing committed ledger is a usage error, not a silent pass
    assert lint_cli.main(
        args + ["--memory", "--ledger-check",
                "--ledger", str(tmp_path / "nope.json")]
    ) == 2


def test_committed_ledger_matches_default_matrix():
    """The committed artifact covers the serial seed cell and carries
    the PJRT evidence + a named culprit for every cell (the full-matrix
    regeneration runs in check.sh; tier-1 pins the shape so a hand-
    edited ledger cannot pass)."""
    doc = memory.load_ledger(memory.DEFAULT_LEDGER)
    assert doc is not None, "artifacts/lint/memory_ledger.json missing"
    assert len(doc["cells"]) >= 70
    for label, cell in doc["cells"].items():
        assert cell["peak_bytes"] <= cell["budget_bytes"], label
        assert cell["pjrt"] is not None, label
        assert cell["largest_temp"]["op"], label


# ---------------------------------------------------------------------------
# the serving surface: gauge + snapshot + doctor block


def test_serve_stamps_peak_hbm_gauge_and_report():
    from mpi_knn_tpu.obs.metrics import get_registry
    from mpi_knn_tpu.serve import ServeSession, build_index
    from mpi_knn_tpu.serve.engine import index_peak_hbm_bytes

    rng = np.random.default_rng(0)
    X = rng.standard_normal((256, 16)).astype(np.float32)
    cfg = KNNConfig(k=4, backend="serial", query_tile=32, corpus_tile=64,
                    query_bucket=32)
    index = build_index(X, cfg)
    session = ServeSession(index)
    session.warm([32])
    peak = index_peak_hbm_bytes(index)
    assert peak > X.nbytes  # the resident corpus is inside the peak
    gauge = get_registry().gauge("serve_peak_hbm_bytes")
    assert gauge.snapshot()["value"] >= peak
    # the session posture snapshot carries it to /healthz
    assert session.stats_snapshot()["peak_hbm_bytes"] == peak
    # and it agrees with the executable's own PJRT figure
    exec_ = next(iter(index._cache.values()))
    assert exec_.peak_hbm_bytes == peak


def test_doctor_memory_probe_agrees():
    from mpi_knn_tpu.resilience.doctor import _memory_probe

    compiled = jax.jit(lambda a: a @ a.T).lower(
        jnp.zeros((8, 8), jnp.float32)
    ).compile()
    block = _memory_probe(compiled)
    assert block["ok"] is True, block
    assert block["predicted_peak_bytes"] > 0
    assert block["disagreements"] == []
    assert block["measured"]["peak_bytes"] > 0
