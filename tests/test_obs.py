"""Unified observability layer (ISSUE 7): the metrics registry, the span
flight recorder, the xplane wire-format parser, device-time attribution,
the `mpi-knn metrics` CLI — and the three acceptance criteria:

(a) a ServeSession run proves ZERO steady-state compiles through the
    SHARED registry (the invariant test_serve/test_ivf/test_resilience
    assert via the same `watch_compiles` scope);
(b) the flight-recorder JSONL reconstructs every batch's dispatch→retire
    interval and every retry/rung event, and SURVIVES a SIGKILL of the
    worker mid-stream (the supervisor recovers and banks the partial
    record — an OPEN batch span in the file IS the kill diagnosis);
(c) a profiled run's per-category device-time split sums to the reported
    busy total (every event carries exactly one category — a split that
    sums past the total is a parser bug, not a measurement).

The xplane parser gets its own unit tests over HAND-BUILT protobuf wire
fixtures (empty plane, multi-line, unknown-field skip, truncated varint):
before ISSUE 7 the parser lived untested in scripts/trace_ops.py, where a
silent misparse would have corrupted every attribution number downstream.
"""

import gzip
import json
import math
import os
import textwrap

import numpy as np
import pytest

from mpi_knn_tpu import KNNConfig, build_index
from mpi_knn_tpu.obs.attribution import attribute_trace, pick_device_plane
from mpi_knn_tpu.obs.metrics import (
    COMPILE_BUCKETS_S,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    get_registry,
    load_snapshot,
    parse_prometheus,
    watch_compiles,
)
from mpi_knn_tpu.obs.spans import (
    FlightRecorder,
    read_flight,
    reconstruct_spans,
    set_recorder,
    summarize_flight,
    to_chrome_trace,
    validate_flight,
)
from mpi_knn_tpu.obs.xplane import (
    ParseError,
    analyze,
    categorize,
    parse_xplane,
    parse_xplane_bytes,
)
from mpi_knn_tpu.resilience import (
    ResiliencePolicy,
    install_faults,
    run_supervised,
)
from mpi_knn_tpu.resilience.ladder import FULL_RUNG
from mpi_knn_tpu.resilience.worker import python_worker_argv
from mpi_knn_tpu.serve import ServeSession


@pytest.fixture(autouse=True)
def _no_leaked_recorder():
    """A test that installs a process recorder must never leak it into
    the next test's serve calls (the span helpers are process-global)."""
    yield
    set_recorder(None)


def _cfg(**kw):
    kw.setdefault("k", 4)
    kw.setdefault("query_tile", 16)
    kw.setdefault("corpus_tile", 32)
    kw.setdefault("query_bucket", 16)
    kw.setdefault("dispatch_depth", 1)
    return KNNConfig(backend="serial", **kw)


# ---------------------------------------------------------------------------
# metrics: counters / gauges / deterministic fixed-bucket histograms


def test_counter_monotonic_rejects_bad_increments():
    c = Counter("c")
    c.inc()
    c.inc(2.5)
    assert c.value == 3.5
    with pytest.raises(ValueError):
        c.inc(-1.0)
    with pytest.raises(ValueError):
        c.inc(math.nan)


def test_gauge_set_add_rejects_nonfinite():
    g = Gauge("g")
    g.set(4.0)
    g.add(-1.5)
    assert g.value == 2.5
    with pytest.raises(ValueError):
        g.set(math.inf)
    with pytest.raises(ValueError):
        g.add(math.nan)


def test_histogram_percentiles_are_deterministic_bucket_bounds():
    """The assertable-percentile contract: the quantile's bucket UPPER
    BOUND, a pure function of the counts — never an interpolation."""
    h = Histogram("h", buckets=(1.0, 2.0, 4.0))
    for v in (0.5, 1.5, 3.0, 9.0):
        h.observe(v)
    assert h.count == 4 and h.sum == 14.0
    assert h.percentile(25) == 1.0
    assert h.percentile(50) == 2.0
    assert h.percentile(75) == 4.0
    assert h.percentile(99) == math.inf  # the 9.0 overflow observation
    with pytest.raises(ValueError):
        h.percentile(101)


def test_histogram_empty_overflow_and_validation():
    h = Histogram("h", buckets=(1.0,))
    assert math.isnan(h.percentile(50))
    with pytest.raises(ValueError):
        h.observe(math.nan)  # a NaN latency is an upstream bug, loudly
    with pytest.raises(ValueError):
        Histogram("bad", buckets=())
    with pytest.raises(ValueError):
        Histogram("bad", buckets=(2.0, 1.0))
    with pytest.raises(ValueError):
        Histogram("bad", buckets=(1.0, 1.0))


def test_registry_get_or_create_and_kind_collision():
    reg = MetricsRegistry()
    c = reg.counter("x", help="first")
    assert reg.counter("x") is c  # get-or-create identity
    with pytest.raises(ValueError):
        reg.gauge("x")  # name re-requested with a different kind
    reg.histogram("lat", buckets=(1.0, 2.0))
    with pytest.raises(ValueError):
        reg.histogram("lat", buckets=(1.0, 3.0))  # different buckets


def test_prometheus_exposition_roundtrips_through_strict_parser():
    reg = MetricsRegistry()
    reg.counter("req_total", help="requests").inc(3)
    reg.gauge("rung").set(1)
    h = reg.histogram("lat_seconds", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)
    text = reg.to_prometheus()
    samples = parse_prometheus(text)
    assert samples["req_total"] == 3.0
    assert samples["rung"] == 1.0
    assert samples['lat_seconds_bucket{le="0.1"}'] == 1.0
    assert samples['lat_seconds_bucket{le="1.0"}'] == 2.0
    assert samples['lat_seconds_bucket{le="+Inf"}'] == 3.0
    assert samples["lat_seconds_count"] == 3.0
    assert samples["lat_seconds_sum"] == pytest.approx(5.55)


def test_parse_prometheus_rejects_malformed():
    for bad in (
        "",  # no samples at all
        "9leading_digit 1",
        "name&bad 1",
        "name not-a-number",
        "dup 1\ndup 2",
        'unterminated{le="x 1',
    ):
        with pytest.raises(ValueError):
            parse_prometheus(bad)


def test_load_snapshot_rejects_non_snapshot_json(tmp_path):
    p = tmp_path / "not-metrics.json"
    p.write_text(json.dumps({"hello": "world"}))
    with pytest.raises(ValueError):
        load_snapshot(str(p))
    reg = MetricsRegistry()
    reg.counter("ok").inc()
    p2 = tmp_path / "snap.json"
    p2.write_text(json.dumps(reg.snapshot()))
    assert "ok" in load_snapshot(str(p2))["metrics"]


def test_watch_compiles_counts_and_feeds_shared_registry():
    """The dedup target: the one scope behind every 'cache hit compiled
    nothing' assertion, AND the same events land in the process-wide
    registry's jax_compiles_total."""
    import jax
    import jax.numpy as jnp

    before = get_registry().counter("jax_compiles_total").value
    with watch_compiles() as counts:
        jax.jit(lambda x: x * 2 + 1)(jnp.ones((3, 7)))
    assert len(counts) >= 1
    assert get_registry().counter("jax_compiles_total").value \
        >= before + len(counts)
    # the duration histogram recorded the same compiles
    assert get_registry().histogram(
        "jax_compile_seconds", buckets=COMPILE_BUCKETS_S
    ).count >= 1


# ---------------------------------------------------------------------------
# span flight recorder


def test_recorder_roundtrip_nesting_and_clean_validation(tmp_path):
    path = str(tmp_path / "f.jsonl")
    rec = FlightRecorder(path)
    with rec.span("outer", cat="serve", a=1) as outer_id:
        with rec.span("inner", cat="serve"):
            rec.event("tick", cat="heartbeat", label="x")
    rec.close()
    records = read_flight(path)
    assert validate_flight(records) == []
    spans, events = reconstruct_spans(records)
    by_name = {s["name"]: s for s in spans}
    assert by_name["inner"]["parent"] == outer_id  # stack-derived nesting
    assert by_name["outer"]["parent"] is None
    assert all(s["dur_s"] is not None and s["dur_s"] >= 0 for s in spans)
    assert events[0]["name"] == "tick"


def test_open_span_is_the_kill_diagnosis(tmp_path):
    path = str(tmp_path / "f.jsonl")
    rec = FlightRecorder(path)
    rec.begin("batch", cat="serve", seq=7)
    # no end: the process "died" here
    rec.close()
    summary = summarize_flight(read_flight(path))
    assert summary["spans_complete"] == 0
    assert summary["open_spans"] == [
        {"name": "batch", "cat": "serve", "attrs": {"seq": 7}}
    ]
    # Chrome export renders the dangling span as a B event
    trace = to_chrome_trace(read_flight(path))
    assert [e["ph"] for e in trace["traceEvents"]] == ["B"]


def test_validate_flight_catches_corruption():
    """Exactly the corruption classes the CI gate must refuse: NaN and
    negative durations, ends without opens, unknown parents, duplicate
    ids, unknown record kinds, unparseable interior lines."""
    ok_b = {"ev": "B", "span": 1, "parent": None, "name": "a", "cat": "",
            "ts": 1.0, "pid": 1, "tid": 1}
    cases = [
        ([{"ev": "Z", "ts": 1.0}], "unknown ev"),
        ([{"ev": "B", "span": 1, "name": "a", "ts": -5.0, "pid": 1}],
         "bad ts"),
        ([ok_b, {"ev": "E", "span": 1, "ts": 2.0, "dur_s": -0.1}],
         "bad dur_s"),
        ([ok_b, {"ev": "E", "span": 1, "ts": 2.0, "dur_s": math.nan}],
         "bad dur_s"),
        ([{"ev": "E", "span": 9, "ts": 1.0, "dur_s": 0.1}], "not open"),
        ([{"ev": "B", "span": 2, "parent": 99, "name": "b", "ts": 1.0,
           "pid": 1}], "never began"),
        ([ok_b, dict(ok_b)], "duplicate span id"),
        ([{"ev": "I", "cat": "", "ts": 1.0, "pid": 1}], "without name"),
        ([{"ev": "?", "raw": "garbage"}], "unparseable"),
    ]
    for records, needle in cases:
        problems = validate_flight(records)
        assert problems and any(needle in p for p in problems), (
            records, needle, problems,
        )
    assert validate_flight(
        [ok_b, {"ev": "E", "span": 1, "ts": 2.0, "dur_s": 0.5}]
    ) == []


def test_ring_rotation_bounds_disk_and_keeps_recent_history(tmp_path):
    path = str(tmp_path / "ring.jsonl")
    rec = FlightRecorder(path, max_bytes=4096)
    for i in range(120):
        rec.event("e", cat="bench", i=i, pad="x" * 64)
    rec.close()
    assert os.path.exists(path) and os.path.exists(path + ".1")
    # bounded at ~2 generations of max_bytes
    assert os.path.getsize(path) <= 4096
    assert os.path.getsize(path + ".1") <= 4096
    records = read_flight(path)
    # previous generation first, newest record last; rotation is one
    # generation deep so the oldest events fell off
    idx = [r["attrs"]["i"] for r in records if r.get("ev") == "I"]
    assert idx == sorted(idx) and idx[-1] == 119 and idx[0] > 0
    with pytest.raises(ValueError):
        FlightRecorder(str(tmp_path / "tiny"), max_bytes=100)


def test_read_flight_torn_tail_skipped_interior_garbage_reported(tmp_path):
    p = tmp_path / "f.jsonl"
    p.write_text(
        '{"ev":"I","name":"a","cat":"","ts":1.0,"pid":1}\n'
        "interior-garbage\n"
        '{"ev":"I","name":"b","cat":"","ts":2.0,"pid":1}\n'
        '{"ev":"B","span":3,"name":"torn-by-the-ki'  # SIGKILL mid-write
    )
    records = read_flight(str(p))
    # the torn TAIL is the one line a kill legitimately produces: skipped
    assert [r.get("name") for r in records if r.get("ev") == "I"] == \
        ["a", "b"]
    # interior garbage is impossible under write+flush: kept and REPORTED
    assert any(r.get("ev") == "?" for r in records)
    assert any("unparseable" in pb for pb in validate_flight(records))


def test_span_helpers_noop_without_recorder_env_arms_them(
    tmp_path, monkeypatch
):
    from mpi_knn_tpu.obs import spans as spans_mod

    monkeypatch.delenv(spans_mod.RECORDER_ENV, raising=False)
    spans_mod.event("nothing")  # must not write anywhere / crash
    assert spans_mod.begin_span("x") is None
    spans_mod.end_span(None)

    path = str(tmp_path / "env.jsonl")
    monkeypatch.setenv(spans_mod.RECORDER_ENV, path)
    with spans_mod.span("from-env", cat="bench"):
        pass
    spans_mod.get_recorder().close()
    names = [s["name"] for s in reconstruct_spans(read_flight(path))[0]]
    assert names == ["from-env"]


def test_chrome_trace_export_shape(tmp_path):
    path = str(tmp_path / "f.jsonl")
    rec = FlightRecorder(path)
    with rec.span("work", cat="serve", seq=0):
        rec.event("mark", cat="retry")
    rec.close()
    doc = to_chrome_trace(read_flight(path))
    assert doc["displayTimeUnit"] == "ms"
    phases = sorted(e["ph"] for e in doc["traceEvents"])
    assert phases == ["X", "i"]
    x = next(e for e in doc["traceEvents"] if e["ph"] == "X")
    assert x["name"] == "work" and x["dur"] >= 0 and x["args"]["seq"] == 0
    # events are time-sorted for the viewer
    ts = [e["ts"] for e in doc["traceEvents"]]
    assert ts == sorted(ts)


# ---------------------------------------------------------------------------
# xplane wire-format parser, over hand-built protobuf fixtures


def _vint(x: int) -> bytes:
    out = bytearray()
    while True:
        b = x & 0x7F
        x >>= 7
        if x:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _ld(fno: int, payload: bytes) -> bytes:  # length-delimited field
    return _vint((fno << 3) | 2) + _vint(len(payload)) + payload


def _vf(fno: int, val: int) -> bytes:  # varint field
    return _vint(fno << 3) + _vint(val)


def _meta(mid: int, name: str, display: str | None = None) -> bytes:
    xmeta = _vf(1, mid) + _ld(2, name.encode())
    if display is not None:
        xmeta += _ld(3, display.encode())
    return _ld(4, _vf(1, mid) + _ld(2, xmeta))  # map<id, XEventMetadata>


def _event(mid: int, off_ps: int, dur_ps: int) -> bytes:
    return _ld(4, _vf(1, mid) + _vf(2, off_ps) + _vf(3, dur_ps))


def _line(name: str, ts_ns: int, events: bytes) -> bytes:
    return _ld(3, _ld(2, name.encode()) + _vf(3, ts_ns) + events)


def _plane(name: str, body: bytes = b"") -> bytes:
    return _ld(1, _ld(2, name.encode()) + body)


def test_xplane_empty_plane_parses_to_no_events():
    raw = _plane("/device:TPU:0")
    assert parse_xplane_bytes(raw) == []
    assert analyze([]) == {}


def test_xplane_multi_line_multi_plane_fixture():
    raw = (
        _plane(
            "/device:TPU:0",
            _meta(1, "dot.1")
            + _meta(2, "sort.2")
            + _line("XLA Ops", 10, _event(1, 5, 100) + _event(2, 200, 50))
            + _line("Steps", 0, _event(1, 0, 7)),
        )
        + _plane("/host:CPU", _meta(9, "hostfn") + _line("t0", 0,
                                                         _event(9, 1, 2)))
    )
    evs = parse_xplane_bytes(raw)
    assert len(evs) == 4
    first = evs[0]
    # start_ps = line timestamp_ns * 1000 + offset_ps
    assert first == {"plane": "/device:TPU:0", "line": "XLA Ops",
                     "name": "dot.1", "start_ps": 10_005, "dur_ps": 100}
    assert {e["plane"] for e in evs} == {"/device:TPU:0", "/host:CPU"}
    assert [e["name"] for e in evs[:3]] == ["dot.1", "sort.2", "dot.1"]


def test_xplane_display_name_wins_and_unknown_metadata_is_labeled():
    raw = _plane(
        "/device:TPU:0",
        _meta(1, "raw-name", display="fusion.7")
        + _line("XLA Ops", 0, _event(1, 0, 5) + _event(42, 0, 3)),
    )
    evs = parse_xplane_bytes(raw)
    assert evs[0]["name"] == "fusion.7"  # display_name overrides name
    assert evs[1]["name"] == "meta:42"  # unknown id labeled, not dropped


def test_xplane_unknown_fields_skipped_by_wire_type():
    """Fields the real schema carries beyond our subset must be skipped
    exactly as a generated proto reader would — varint, fixed64, fixed32
    and length-delimited unknowns at every nesting level."""
    fixed64 = _vint((99 << 3) | 1) + (1234).to_bytes(8, "little")
    fixed32 = _vint((98 << 3) | 5) + (99).to_bytes(4, "little")
    unknown_ld = _ld(97, b"opaque-submessage")
    unknown_varint = _vf(96, 7)
    raw = (
        unknown_varint  # XSpace-level unknown
        + _plane(
            "/device:TPU:0",
            fixed64  # XPlane-level unknown
            + _meta(1, "dot.1")
            + _line(
                "XLA Ops", 0,
                _ld(4, _vf(1, 1) + _vf(2, 11) + _vf(3, 13)
                    + fixed32 + unknown_ld)  # XEvent-level unknowns
            ),
        )
    )
    evs = parse_xplane_bytes(raw)
    assert evs == [{"plane": "/device:TPU:0", "line": "XLA Ops",
                    "name": "dot.1", "start_ps": 11, "dur_ps": 13}]


def test_xplane_truncated_and_garbage_raise_parse_error():
    with pytest.raises(ParseError):
        parse_xplane_bytes(b"\xff")  # truncated varint
    with pytest.raises(ParseError):
        parse_xplane_bytes(b"\xff" * 12)  # varint overruns 64 bits
    with pytest.raises(ParseError):
        parse_xplane_bytes(_vint(1 << 3 | 2) + _vint(100) + b"short")
    with pytest.raises(ParseError):
        parse_xplane_bytes(_vint(1 << 3 | 3))  # group wire type
    # truncation INSIDE a nested message surfaces too (plane payload is
    # length-delimited, so the inner parse sees a clean truncated buffer)
    good = _plane("/device:TPU:0", _meta(1, "dot.1"))
    with pytest.raises(ParseError):
        parse_xplane_bytes(good[:-3])


def test_parse_xplane_reads_gz_files(tmp_path):
    raw = _plane("/device:TPU:0",
                 _meta(1, "dot.1") + _line("l", 0, _event(1, 0, 9)))
    p = tmp_path / "t.xplane.pb.gz"
    p.write_bytes(gzip.compress(raw))
    evs = parse_xplane(str(p))
    assert len(evs) == 1 and evs[0]["dur_ps"] == 9


def test_categorize_and_analyze_busy_split_with_overlap():
    assert categorize("collective-permute-start.1") == "collective"
    assert categorize("sort.42") == "sort-topk"
    assert categorize("loop_fusion.3") == "matmul"
    assert categorize("dynamic-update-slice.9") == "copy"
    assert categorize("parameter.0") == "other"

    ms = 1_000_000_000  # 1 ms in ps
    events = [
        {"plane": "p", "line": "l", "name": "dot.1",
         "start_ps": 0, "dur_ps": 10 * ms},
        {"plane": "p", "line": "l", "name": "ppermute.2",
         "start_ps": 5 * ms, "dur_ps": 10 * ms},  # 5 ms under the dot
        {"plane": "p", "line": "l", "name": "zero-dur", "start_ps": 0,
         "dur_ps": 0},  # zero-duration events are not busy time
    ]
    rep = analyze(events)["p"]
    assert rep["busy_ms_by_category"] == {"collective": 10.0,
                                          "matmul": 10.0}
    assert rep["collective_total_ms"] == 10.0
    assert rep["collective_overlapped_with_matmul_ms"] == 5.0
    assert rep["collective_span_ms"] == 0  # no async start/done pairs
    assert rep["top_ops_ms"] == {"dot.1": 10.0, "ppermute.2": 10.0}


def test_dma_wait_is_its_own_category_not_matmul(tmp_path):
    """The fused rotation's in-kernel semaphore stalls must never be
    counted as compute: a collective span overlapping a stalled kernel
    is time the overlap FAILED to hide, and folding the wait into
    'matmul' would credit exactly that time to overlap_fraction."""
    assert categorize("DmaWait.3") == "dma-wait"
    assert categorize("wait-semaphore.1") == "dma-wait"
    assert categorize("dma_wait (fused ring)") == "dma-wait"
    # '-done' halves of async collectives keep their collective category
    # (the span pairing depends on it)
    assert categorize("collective-permute-done.2") == "collective"

    ms = 1_000_000_000
    raw = _plane(
        "/device:TPU:0",
        _meta(1, "dot.1") + _meta(2, "dma-wait.2")
        + _meta(3, "collective-permute-start.3")
        + _meta(4, "collective-permute-done.3")
        + _line(
            "XLA Ops", 0,
            _event(1, 0, 10 * ms)          # compute 0–10
            + _event(2, 10 * ms, 4 * ms)   # kernel stalls on the wire 10–14
            + _event(3, 8 * ms, 1 * ms)    # DMA in flight 8–14
            + _event(4, 13 * ms, 1 * ms),
        ),
    )
    (tmp_path / "t.xplane.pb").write_bytes(raw)
    out = attribute_trace(str(tmp_path))
    assert out["busy_ms"]["dma-wait"] == 4.0
    assert out["dma_wait_ms"] == 4.0
    assert out["busy_ms"]["matmul"] == 10.0
    # the invariant: every event still lands in exactly one category
    assert out["busy_total_ms"] == pytest.approx(
        sum(out["busy_ms"].values()), abs=1e-6
    )
    # span 8–14 overlaps true compute only on 8–10: 2 of 6 ms hidden.
    # Were the stall miscategorized as matmul, this would read 6/6.
    assert out["collective_span_ms"] == 6.0
    assert out["collective_span_overlapped_with_matmul_ms"] == 2.0
    assert out["overlap_fraction"] == pytest.approx(2 / 6, abs=1e-4)


# ---------------------------------------------------------------------------
# device-time attribution


def test_attribute_trace_split_sums_and_casualties(tmp_path):
    ms = 1_000_000_000
    raw = _plane(
        "/device:TPU:0",
        _meta(1, "dot.1") + _meta(2, "sort.2") + _meta(3, "copy.3")
        + _line("XLA Ops", 0,
                _event(1, 0, 8 * ms) + _event(2, 8 * ms, 3 * ms)
                + _event(3, 11 * ms, 1 * ms)),
    )
    (tmp_path / "good.xplane.pb").write_bytes(raw)
    (tmp_path / "bad.xplane.pb").write_bytes(b"\xff\xff\xff")
    out = attribute_trace(str(tmp_path))
    assert out["plane"] == "/device:TPU:0"
    # the acceptance invariant: categories sum to the busy total
    assert out["busy_total_ms"] == pytest.approx(
        sum(out["busy_ms"].values()), abs=1e-6
    )
    assert out["busy_ms"] == {"matmul": 8.0, "sort-topk": 3.0, "copy": 1.0}
    assert out["overlap_fraction"] is None  # no collectives in this trace
    # the truncated sibling is a recorded casualty, not an abort
    assert [c["file"] for c in out["casualties"]] == [
        str(tmp_path / "bad.xplane.pb")
    ]


def test_attribute_trace_errors_are_explicit(tmp_path):
    out = attribute_trace(str(tmp_path))
    assert "error" in out and "no .xplane.pb" in out["error"]
    (tmp_path / "bad.xplane.pb").write_bytes(b"\xff\xff\xff")
    out = attribute_trace(str(tmp_path))
    assert "error" in out and out["casualties"]


def test_pick_device_plane_prefers_device_over_busier_host():
    planes = {
        "/host:CPU": {"busy_ms_by_category": {"other": 100.0}},
        "/device:TPU:0": {"busy_ms_by_category": {"matmul": 1.0}},
        "/device:TPU:1": {"busy_ms_by_category": {"matmul": 2.0}},
    }
    assert pick_device_plane(planes) == "/device:TPU:1"
    assert pick_device_plane({}) is None
    # CPU traces put the op events on a host plane: the right (only) story
    assert pick_device_plane(
        {"/host:CPU": {"busy_ms_by_category": {"other": 1.0}}}
    ) == "/host:CPU"


# ---------------------------------------------------------------------------
# `mpi-knn metrics` CLI


def _snapshot_file(tmp_path) -> str:
    reg = MetricsRegistry()
    reg.counter("req_total").inc(2)
    reg.histogram("lat", buckets=(0.1, 1.0)).observe(0.05)
    p = tmp_path / "snap.json"
    p.write_text(json.dumps(reg.snapshot()))
    return str(p)


def test_metrics_cli_renders_and_checks_snapshot(tmp_path, capsys):
    from mpi_knn_tpu.obs.cli import main as metrics_main

    snap = _snapshot_file(tmp_path)
    assert metrics_main([snap]) == 0
    out = capsys.readouterr().out
    assert "req_total 2.0" in out and 'lat_bucket{le="+Inf"} 1' in out
    assert metrics_main([snap, "--format", "json"]) == 0
    assert json.loads(capsys.readouterr().out)["metrics"]["req_total"]
    assert metrics_main([snap, "--check"]) == 0
    assert json.loads(capsys.readouterr().out)["ok"] is True


def test_metrics_cli_flight_modes(tmp_path, capsys):
    from mpi_knn_tpu.obs.cli import main as metrics_main

    path = str(tmp_path / "f.jsonl")
    rec = FlightRecorder(path)
    with rec.span("batch", cat="serve", seq=0):
        pass
    rec.begin("open-at-death", cat="bench")
    rec.close()

    assert metrics_main(["--flight", path]) == 0  # summary
    summary = json.loads(capsys.readouterr().out)
    assert summary["records"] == 3
    assert summary["open_spans"][0]["name"] == "open-at-death"

    assert metrics_main(["--flight", path, "--validate"]) == 0
    chrome = str(tmp_path / "trace.json")
    assert metrics_main(["--flight", path, "--chrome", chrome]) == 0
    assert json.load(open(chrome))["traceEvents"]

    # schema problems and empty records exit 1 (the CI gate)
    with open(path, "a") as f:
        f.write('{"ev":"E","span":99,"ts":1.0,"dur_s":-2}\n'
                '{"ev":"I","name":"pad","cat":"","ts":1.0,"pid":1}\n')
    assert metrics_main(["--flight", path, "--validate"]) == 1
    empty = str(tmp_path / "none.jsonl")
    open(empty, "w").close()
    assert metrics_main(["--flight", empty, "--validate"]) == 1
    assert metrics_main(["--flight", empty]) == 1


def test_metrics_cli_usage_and_load_errors(tmp_path, capsys):
    from mpi_knn_tpu.obs.cli import main as metrics_main

    snap = _snapshot_file(tmp_path)
    assert metrics_main([]) == 2  # neither snapshot nor --flight
    assert metrics_main([snap, "--flight", "x.jsonl"]) == 2  # both
    assert metrics_main([snap, "--validate"]) == 2  # flight-only flag
    assert metrics_main([str(tmp_path / "missing.json")]) == 1
    bad = tmp_path / "bad.json"
    bad.write_text('{"not": "a snapshot"}')
    assert metrics_main([str(bad)]) == 1
    capsys.readouterr()


def test_metrics_subcommand_routed_from_main_cli(tmp_path, capsys):
    from mpi_knn_tpu.cli import main as cli_main

    assert cli_main(["metrics", _snapshot_file(tmp_path)]) == 0
    assert "req_total" in capsys.readouterr().out


# ---------------------------------------------------------------------------
# acceptance (a): zero steady-state compiles, proven via the SHARED registry


def test_serve_zero_steady_state_compiles_via_shared_registry(rng):
    X = rng.standard_normal((96, 12)).astype(np.float32)
    sess = ServeSession(build_index(X, _cfg()))
    sess.warm([16, 32])
    sizes = (5, 16, 17, 32, 9)  # ragged sizes, both warmed buckets
    for rows in sizes:  # first pass: reach steady state at every shape
        sess.submit(rng.standard_normal((rows, 12)).astype(np.float32))
    sess.drain()
    reg = get_registry()
    compiles_before = reg.counter("jax_compiles_total").value
    batches_before = reg.counter("serve_batches_total").value
    lat_before = reg.histogram("serve_batch_latency_seconds").count
    for rows in sizes:  # steady state: same shapes again
        sess.submit(rng.standard_normal((rows, 12)).astype(np.float32))
    sess.drain()
    # the same invariant test_serve/test_ivf assert, now a registry fact
    assert reg.counter("jax_compiles_total").value == compiles_before
    assert reg.counter("serve_batches_total").value == batches_before + 5
    assert reg.histogram("serve_batch_latency_seconds").count == \
        lat_before + 5


# ---------------------------------------------------------------------------
# acceptance (b): the flight record reconstructs the stream — and survives


def test_flight_reconstructs_batches_retries_and_rung_walk(rng, tmp_path):
    path = str(tmp_path / "flight.jsonl")
    set_recorder(FlightRecorder(path, fresh=True))
    X = rng.standard_normal((128, 16)).astype(np.float32)
    idx = build_index(X, _cfg(query_tile=16, corpus_tile=32))
    # deadline wide enough that a clean (or retried) CPU batch never
    # breaches it; only the injected 0.5 s slow batch does
    pol = ResiliencePolicy(
        max_retries=3, backoff_base_s=0.01, batch_deadline_s=0.25,
        degrade_after=1, min_bucket=16,
    )
    sess = ServeSession(idx, resilience=pol)
    sess.warm([8])
    Q = rng.standard_normal((8, 16)).astype(np.float32)
    with install_faults({"serve-batch": ("transient", 2)}):
        sess.submit(Q)  # batch 0: retried twice, then served
    with install_faults({"serve-batch": ("slow", 0.5)}):
        sess.submit(Q)  # batch 1: breaches the deadline → rung shed
    sess.submit(Q)      # batch 2: clean, at the degraded rung
    set_recorder(None)  # close + flush

    records = read_flight(path)
    assert validate_flight(records) == []
    spans, events = reconstruct_spans(records)

    # the index build and warm/compile story is in the same record
    assert any(s["name"] == "index-build" and s["cat"] == "index"
               for s in spans)
    assert any(s["name"] == "compile" and s["cat"] == "compile"
               for s in spans)

    # every batch's dispatch→retire interval reconstructs, closed, with
    # the same honest latency the session reported
    batches = sorted((s for s in spans if s["name"] == "batch"),
                     key=lambda s: s["attrs"]["seq"])
    assert [b["attrs"]["seq"] for b in batches] == [0, 1, 2]
    for b, res_lat in zip(batches, sess.latencies):
        assert b["dur_s"] is not None and b["dur_s"] >= 0
        assert b["end_attrs"]["latency_s"] == res_lat
    assert batches[0]["end_attrs"]["retries"] == 2
    assert batches[1]["end_attrs"]["deadline_breached"] is True
    assert batches[0]["attrs"]["rung"] == FULL_RUNG
    assert batches[2]["attrs"]["rung"] != FULL_RUNG  # walked

    # retry and rung-change events carry their provenance
    retry = next(e for e in events if e["name"] == "retry")
    assert retry["attrs"]["seq"] == 0 and retry["attrs"]["retries"] == 2
    assert retry["attrs"]["backoffs"] == [0.01, 0.02]
    degrade = next(e for e in events if e["name"] == "degrade")
    assert degrade["attrs"]["after_batch"] == 1
    assert degrade["attrs"]["rung"] == batches[2]["attrs"]["rung"]
    # heartbeat marks mirror into the same timeline
    assert any(e["name"] == "beat" for e in events)


def test_flight_record_survives_sigkill_of_worker_mid_stream(tmp_path):
    """The BENCH_r01/r03/r04/r05 failure mode, closed: a worker
    SIGKILLed mid-batch leaves a readable record up to the instant of
    death; the supervisor recovers it, banks the summary, and the open
    batch span IS the diagnosis."""
    script = textwrap.dedent("""
        import os, signal, threading
        import numpy as np
        from mpi_knn_tpu import KNNConfig, build_index
        from mpi_knn_tpu.serve import ServeSession

        rng = np.random.default_rng(0)
        X = rng.standard_normal((96, 8)).astype(np.float32)
        cfg = KNNConfig(backend="serial", k=3, query_tile=16,
                        corpus_tile=32, query_bucket=16, dispatch_depth=1)
        sess = ServeSession(build_index(X, cfg))
        sess.warm([16])
        Q = rng.standard_normal((16, 8)).astype(np.float32)
        sess.submit(Q)
        sess.submit(Q)
        # batch 2's dispatch hangs at the injected fault site; the timer
        # SIGKILLs this process mid-batch — no cleanup, no atexit
        threading.Timer(
            1.0, lambda: os.kill(os.getpid(), signal.SIGKILL)
        ).start()
        sess.submit(Q)
    """)
    flight = str(tmp_path / "flight.jsonl")
    env = dict(os.environ, TKNN_FAULTS="serve-batch=hang:3")
    res = run_supervised(
        python_worker_argv("-c", script),
        env=env, beat_timeout_s=None, wall_timeout_s=240.0,
        flight_path=flight,
    )
    assert res.status == "crashed"  # SIGKILL, not a supervisor kill
    # the supervisor banked the partial record alongside the failure
    assert res.flight is not None and res.flight["records"] > 0
    assert any(s["name"] == "batch" for s in res.flight["open_spans"])
    # the caller-owned JSONL reconstructs the stream up to the kill:
    # two retired batches, the third open at the instant of death
    spans, _ = reconstruct_spans(read_flight(flight))
    batches = sorted((s for s in spans if s["name"] == "batch"),
                     key=lambda s: s["attrs"]["seq"])
    assert [b["attrs"]["seq"] for b in batches] == [0, 1, 2]
    assert batches[0]["dur_s"] is not None
    assert batches[1]["dur_s"] is not None
    assert batches[2]["dur_s"] is None  # the kill diagnosis


# ---------------------------------------------------------------------------
# acceptance (c): profiled run — per-category split sums to the busy total


def test_profile_device_time_split_sums_to_busy_total(rng, tmp_path):
    X = rng.standard_normal((96, 12)).astype(np.float32)
    sess = ServeSession(build_index(X, _cfg()))
    sess.warm([16])
    Q = rng.standard_normal((16, 12)).astype(np.float32)
    sess.submit(Q)  # steady state: the profiled batches compile nothing
    out = sess.profile([Q, Q], trace_dir=str(tmp_path / "prof"))
    assert out["batches_profiled"] == 2
    assert out["trace_dir"] == str(tmp_path / "prof")
    assert "busy_ms" in out, out
    assert out["busy_total_ms"] > 0
    assert set(out["busy_ms"]) <= {
        "matmul", "sort-topk", "collective", "copy", "other"
    }
    assert all(v >= 0 for v in out["busy_ms"].values())
    # the acceptance invariant: categories sum to ≤ the busy total (they
    # sum EXACTLY to it — every event carries exactly one category; the
    # tolerance covers the per-category ms rounding)
    assert sum(out["busy_ms"].values()) <= out["busy_total_ms"] + 1e-6
    assert out["busy_total_ms"] == pytest.approx(
        sum(out["busy_ms"].values()), abs=1e-6
    )
    if out["overlap_fraction"] is not None:
        assert 0.0 <= out["overlap_fraction"] <= 1.0


# ---------------------------------------------------------------------------
# review regressions: survivable errors close their spans, doctor-verdict
# snapshots load, inert CLI knobs refuse, profile pre-compiles its buckets


def test_poisoned_and_exhausted_batches_close_their_spans(rng, tmp_path):
    """An OPEN span is the contract's kill diagnosis — a raised-and-CAUGHT
    serving error (sentinel trip, retries exhausted) must close the batch
    span with an error attr, not forge a mid-batch death for a process
    that is still serving."""
    from mpi_knn_tpu.resilience.ladder import PoisonedResultError
    from mpi_knn_tpu.resilience.retry import RetryExhausted

    path = str(tmp_path / "flight.jsonl")
    set_recorder(FlightRecorder(path, fresh=True))
    X = rng.standard_normal((96, 12)).astype(np.float32)
    pol = ResiliencePolicy(max_retries=1, backoff_base_s=0.01)
    sess = ServeSession(build_index(X, _cfg()), resilience=pol)
    sess.warm([16])
    Q = rng.standard_normal((16, 12)).astype(np.float32)

    with install_faults({"serve-nan": "nan"}):
        with pytest.raises(PoisonedResultError):
            sess.submit(Q)  # sentinel trips at retire (dispatch_depth=1)
    with install_faults({"serve-batch": ("transient", 5)}):
        with pytest.raises(RetryExhausted):
            sess.submit(Q)  # 1 retry allowed, 5 needed: exhausted
    sess.submit(Q)  # the session survives and serves on
    sess.drain()
    set_recorder(None)

    records = read_flight(path)
    assert validate_flight(records) == []
    spans, _ = reconstruct_spans(records)
    batches = [s for s in spans if s["name"] == "batch"]
    assert len(batches) == 3
    assert all(s["dur_s"] is not None for s in batches)  # none left open
    errors = [s["end_attrs"].get("error") for s in batches]
    assert "poisoned-result" in errors and "RetryExhausted" in errors
    assert errors.count(None) == 1  # the clean batch
    assert summarize_flight(records)["open_spans"] == []


def test_load_snapshot_unwraps_doctor_verdict(tmp_path, capsys):
    """The CLI help documents reading a doctor verdict; the verdict nests
    the registry snapshot under its "metrics" key. load_snapshot unwraps
    by schema marker instead of crashing in to_prometheus."""
    from mpi_knn_tpu.obs.cli import main as metrics_main

    reg = MetricsRegistry()
    reg.counter("jax_compiles_total").inc()
    p = tmp_path / "verdict.json"
    p.write_text(json.dumps(
        {"ok": True, "status": "ok", "metrics": reg.snapshot(),
         "flight": None}
    ))
    assert "jax_compiles_total" in load_snapshot(str(p))["metrics"]
    assert metrics_main([str(p)]) == 0  # renders, no traceback
    assert "jax_compiles_total" in capsys.readouterr().out
    assert metrics_main([str(p), "--check"]) == 0
    capsys.readouterr()
    # a verdict whose probe died before printing metrics refuses loudly
    p2 = tmp_path / "verdict-null.json"
    p2.write_text(json.dumps({"ok": False, "metrics": None}))
    assert metrics_main([str(p2)]) == 1
    capsys.readouterr()


def test_metrics_cli_refuses_snapshot_flags_with_flight(tmp_path, capsys):
    """The inert-knob refusal convention: `--flight F --check` must exit
    2, not print a span summary while the CI check silently never ran."""
    from mpi_knn_tpu.obs.cli import main as metrics_main

    path = str(tmp_path / "f.jsonl")
    rec = FlightRecorder(path)
    with rec.span("batch", cat="serve"):
        pass
    rec.close()
    assert metrics_main(["--flight", path, "--check"]) == 2
    assert metrics_main(["--flight", path, "--format", "json"]) == 2
    capsys.readouterr()


def test_profile_compiles_unserved_bucket_before_trace(rng, tmp_path):
    """A profile batch size the stream never served must compile BEFORE
    the jax.profiler trace opens — a cold compile inside the trace lands
    in "other" and the "steady-state" split measures compilation."""
    path = str(tmp_path / "flight.jsonl")
    set_recorder(FlightRecorder(path, fresh=True))
    X = rng.standard_normal((96, 12)).astype(np.float32)
    sess = ServeSession(build_index(X, _cfg()))
    sess.warm([16])
    # 48 rows pads to bucket 64 — a cell warm() never compiled
    Q = rng.standard_normal((48, 12)).astype(np.float32)
    sess.profile([Q], trace_dir=str(tmp_path / "trace"))
    set_recorder(None)

    spans, _ = reconstruct_spans(read_flight(path))
    prof = next(s for s in spans if s["name"] == "profile")
    compiles = [s for s in spans if s["name"] == "compile"]
    assert any(s["attrs"]["bucket"] == 64 for s in compiles)
    assert all(s["ts"] + s["dur_s"] <= prof["ts"] for s in compiles)


def test_compile_failure_closes_its_span(rng, tmp_path, monkeypatch):
    """A raised lowering/compile failure is survivable by the caller —
    the compile span must close with the error, not forge an open-span
    'killed mid-compile' diagnosis."""
    from mpi_knn_tpu.serve import engine as serve_engine

    path = str(tmp_path / "flight.jsonl")
    set_recorder(FlightRecorder(path, fresh=True))
    X = rng.standard_normal((96, 12)).astype(np.float32)
    sess = ServeSession(build_index(X, _cfg()))

    def boom(*a, **k):
        raise RuntimeError("injected lowering failure")

    monkeypatch.setattr(serve_engine, "lower_bucket", boom)
    with pytest.raises(RuntimeError):
        sess.submit(rng.standard_normal((16, 12)).astype(np.float32))
    set_recorder(None)

    records = read_flight(path)
    spans, _ = reconstruct_spans(records)
    comp = [s for s in spans if s["name"] == "compile"]
    assert comp and all(s["dur_s"] is not None for s in comp)
    assert any(s["end_attrs"].get("error") == "RuntimeError" for s in comp)
    # the enclosing batch span closed too: nothing left open
    assert summarize_flight(records)["open_spans"] == []


def test_validate_tolerates_rotated_ring_prefix(tmp_path):
    """A long-lived server's ring file that rotated twice starts at a
    generation marker; ends/parents referencing the dropped prefix are
    the ring working as designed, not corruption — the CI gate must not
    fail a healthy server's record."""
    path = str(tmp_path / "ring.jsonl")
    rec = FlightRecorder(path, max_bytes=4096)
    for i in range(400):
        with rec.span("batch", cat="serve", i=i, pad="x" * 64):
            pass
    rec.close()
    records = read_flight(path)
    assert records[0]["ev"] == "R"  # first retained record: ring marker
    assert validate_flight(records) == []
    # genuine corruption still reports on a truncated record
    assert any("bad dur_s" in p for p in validate_flight(
        records + [{"ev": "E", "span": 10 ** 9, "ts": 1.0, "dur_s": -1.0}]
    ))
    # and WITHOUT a truncation marker a dangling end is still a problem
    assert any("not open" in p for p in validate_flight(
        [{"ev": "E", "span": 5, "ts": 1.0, "dur_s": 0.1}]
    ))
    # a marker with a bad generation is itself a problem
    assert any("ring marker" in p for p in validate_flight(
        [{"ev": "R", "gen": 0, "ts": 1.0}]
    ))


def test_metrics_cli_validate_and_chrome_compose(tmp_path, capsys):
    """`--validate --chrome OUT` must write OUT, not silently drop the
    export because validation returned first."""
    from mpi_knn_tpu.obs.cli import main as metrics_main

    path = str(tmp_path / "f.jsonl")
    rec = FlightRecorder(path)
    with rec.span("batch", cat="serve"):
        pass
    rec.close()
    out = str(tmp_path / "t.json")
    assert metrics_main(
        ["--flight", path, "--validate", "--chrome", out]
    ) == 0
    assert json.load(open(out))["traceEvents"]
    capsys.readouterr()


# ---------------------------------------------------------------------------
# labeled counters/gauges (ISSUE 11: the per-tenant axis)


def test_labeled_counters_round_trip_prometheus():
    """Labeled series render as canonical samples under ONE HELP/TYPE
    header per base family, and the strict parser reads them back."""
    from mpi_knn_tpu.obs.metrics import (
        MetricsRegistry,
        parse_prometheus,
        to_prometheus,
    )

    reg = MetricsRegistry()
    reg.counter("served_total", help="rows", labels={"tenant": "a"}).inc(3)
    reg.counter("served_total", help="rows", labels={"tenant": "b"}).inc(5)
    reg.counter("served_total", help="rows",
                labels={"tenant": "a"}).inc(2)  # same series, get-or-create
    reg.gauge("depth", labels={"queue": "q0"}).set(7)
    text = to_prometheus(reg.snapshot())
    samples = parse_prometheus(text)
    assert samples['served_total{tenant="a"}'] == 5.0
    assert samples['served_total{tenant="b"}'] == 5.0
    assert samples['depth{queue="q0"}'] == 7.0
    type_lines = [ln for ln in text.splitlines() if ln.startswith("# TYPE")]
    assert type_lines.count("# TYPE served_total counter") == 1


def test_label_canonicalization_and_validation():
    """Key order never forks a series; hostile values are refused (an
    escaping-needed value would corrupt the exposition silently)."""
    from mpi_knn_tpu.obs.metrics import MetricsRegistry, sample_name

    assert sample_name("m", {"b": 1, "a": 2}) == 'm{a="2",b="1"}'
    reg = MetricsRegistry()
    c1 = reg.counter("m", labels={"a": "x", "b": "y"})
    c2 = reg.counter("m", labels={"b": "y", "a": "x"})
    assert c1 is c2
    with pytest.raises(ValueError, match="escaping"):
        reg.counter("m", labels={"a": 'inj"ect'})
    with pytest.raises(ValueError, match="bad label name"):
        reg.counter("m", labels={"0bad": "v"})
    with pytest.raises(ValueError, match="bad metric name"):
        reg.counter("bad name")


def test_histograms_refuse_labels():
    """A labeled histogram cannot be rendered correctly by name-keyed
    storage (the _bucket suffix belongs before the labels) — refused
    loudly rather than emitting malformed exposition."""
    from mpi_knn_tpu.obs.metrics import MetricsRegistry

    with pytest.raises(ValueError, match="labels are not supported"):
        MetricsRegistry().histogram("lat", labels={"tenant": "a"})


def test_mixed_kind_family_guard_spans_labels():
    """A labeled counter and a bare gauge (or any other kind) sharing
    one BASE family name must collide loudly — they would render a
    mixed-kind family under one TYPE header (review regression)."""
    from mpi_knn_tpu.obs.metrics import MetricsRegistry

    reg = MetricsRegistry()
    reg.counter("fam_total", labels={"tenant": "a"})
    with pytest.raises(ValueError, match="already registered as counter"):
        reg.gauge("fam_total")
    with pytest.raises(ValueError, match="already registered as counter"):
        reg.histogram("fam_total")
    # same kind, other labels (or bare) stays fine
    reg.counter("fam_total", labels={"tenant": "b"})
    reg.counter("fam_total")
    reg.clear()
    reg.gauge("fam_total")  # clear() resets the family map too
