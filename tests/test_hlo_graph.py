"""Direct unit tests for the ``utils.hlo_graph`` parser — the parsing core
under the lint engine. The overlap test exercises it end-to-end; these pin
the grammar corners on their own: ``control-predecessors``,
``branch_computations``, multi-computation ``calls``, the two surface
syntaxes (``%``-prefixed dump format vs the bare-name
``compiler_ir("hlo")`` format), ``} // name`` computation closers, and the
result-type capture the memory rule depends on."""

from mpi_knn_tpu.utils.hlo_graph import backward_slice, parse_hlo

_BRANCHY = """\
HloModule branchy, entry_computation_layout={(f32[8]{0})->f32[8]{0}}

%big.1 (bp.1: f32[8]) -> f32[8] {
  %bp.1 = f32[8]{0} parameter(0)
  ROOT %bd.1 = f32[8]{0} multiply(%bp.1, %bp.1)
}

%small.1 (sp.1: f32[8]) -> f32[8] {
  %sp.1 = f32[8]{0} parameter(0)
  ROOT %sd.1 = f32[8]{0} add(%sp.1, %sp.1)
}

%helper.1 (hp.1: f32[8]) -> f32[8] {
  %hp.1 = f32[8]{0} parameter(0)
  ROOT %hr.1 = f32[8]{0} negate(%hp.1)
}

ENTRY %main.1 (a.1: f32[8], i.1: s32[]) -> f32[8] {
  %a.1 = f32[8]{0} parameter(0)
  %i.1 = s32[] parameter(1)
  %c.1 = f32[8]{0} conditional(%i.1, %a.1, %a.1), branch_computations={%big.1, %small.1}
  %cc.1 = f32[8]{0} custom-call(%c.1), custom_call_target="fake", called_computations={%helper.1, %big.1}
  ROOT %r.1 = f32[8]{0} add(%c.1, %cc.1)
}
"""


def test_branch_computations_and_called_computations_sets():
    """Both set-valued attribute forms create call edges: a conditional's
    ``branch_computations`` and a custom-call's ``called_computations``
    (each possibly multi-computation)."""
    m = parse_hlo(_BRANCHY)
    assert set(m.computations) == {"big.1", "small.1", "helper.1", "main.1"}
    cond = m.instr("main.1", "c.1")
    assert cond.called == ["big.1", "small.1"]
    cc = m.instr("main.1", "cc.1")
    assert cc.called == ["helper.1", "big.1"]
    # the slice of the root reaches through BOTH branches and the helper
    sl = backward_slice(m, "main.1", "r.1")
    comps = {c for c, _ in sl}
    assert {"big.1", "small.1", "helper.1"} <= comps


def test_control_predecessors_parse_and_count_as_edges():
    mod = """\
HloModule ctrl, entry_computation_layout={(f32[4]{0})->f32[4]{0}}

ENTRY %e.1 (p.1: f32[4]) -> f32[4] {
  %p.1 = f32[4]{0} parameter(0)
  %x.1 = f32[4]{0} multiply(%p.1, %p.1)
  %y.1 = f32[4]{0} add(%p.1, %p.1), control-predecessors={%x.1}
  ROOT %r.1 = f32[4]{0} negate(%y.1)
}
"""
    m = parse_hlo(mod)
    y = m.instr("e.1", "y.1")
    assert y.controls == ["x.1"]
    assert ("e.1", "x.1") in backward_slice(m, "e.1", "y.1")


_BARE = """\
HloModule bare, entry_computation_layout={(f32[4,8]{1,0})->f32[4,4]{1,0}}

region_0.1 {
  Arg_0.2 = f32[4,8]{1,0} parameter(0)
  transpose.3 = f32[8,4]{0,1} transpose(Arg_0.2), dimensions={1,0}
  ROOT dot.4 = f32[4,4]{1,0} dot(Arg_0.2, transpose.3), lhs_contracting_dims={1}, rhs_contracting_dims={0}
} // region_0.1

ENTRY main.5 {
  a.6 = f32[4,8]{1,0} parameter(0)
  call.7 = f32[4,4]{1,0} call(a.6), to_apply=region_0.1
  constant.8 = f32[] constant(1)
  broadcast.9 = f32[4,4]{1,0} broadcast(constant.8), dimensions={}
  ROOT add.10 = f32[4,4]{1,0} add(call.7, broadcast.9)
}
"""


def test_bare_name_format_and_comment_closers():
    """The ``compiler_ir("hlo")`` surface syntax: no ``%`` prefixes, headers
    without parameter lists, computations closed by ``} // name``. The old
    parser silently swallowed everything after the first commented closer —
    which is how a whole dump once reported zero collective-permutes."""
    m = parse_hlo(_BARE)
    assert set(m.computations) == {"region_0.1", "main.5"}
    assert m.computations["main.5"].is_entry
    call = m.instr("main.5", "call.7")
    assert call.operands == ["a.6"]
    assert call.called == ["region_0.1"]
    # literal operands (constant(1), parameter(0)) must not become edges
    assert m.instr("main.5", "constant.8").operands == []
    sl = backward_slice(m, "main.5", "add.10")
    assert ("region_0.1", "dot.4") in sl


def test_result_types_captured_for_shape_accounting():
    m = parse_hlo(_BARE)
    assert m.instr("main.5", "a.6").type_str == "f32[4,8]{1,0}"
    assert m.instr("main.5", "constant.8").type_str == "f32[]"
    mt = parse_hlo(
        """\
HloModule t, entry_computation_layout={(f32[2]{0})->(f32[2]{0}, s32[2]{0})}

ENTRY %e.1 (p.1: f32[2]) -> (f32[2], s32[2]) {
  %p.1 = f32[2]{0} parameter(0)
  %i.1 = s32[2]{0} convert(%p.1)
  ROOT %t.1 = (f32[2]{0}, s32[2]{0}) tuple(%p.1, %i.1)
}
"""
    )
    assert mt.instr("e.1", "t.1").type_str == "(f32[2]{0}, s32[2]{0})"


_COND_ARGS = """\
HloModule condargs, entry_computation_layout={(f32[4,4]{1,0}, pred[])->f32[4,4]{1,0}}

%b0.1 (p0.1: f32[4,4]) -> f32[4,4] {
  %p0.1 = f32[4,4]{1,0} parameter(0)
  ROOT %cp.1 = f32[4,4]{1,0} collective-permute(%p0.1), source_target_pairs={{0,1},{1,0}}
}

%b1.1 (p1.1: f32[4,4]) -> f32[4,4] {
  %p1.1 = f32[4,4]{1,0} parameter(0)
  ROOT %neg.1 = f32[4,4]{1,0} negate(%p1.1)
}

ENTRY %main.1 (a.1: f32[4,4], pr.1: pred[]) -> f32[4,4] {
  %a.1 = f32[4,4]{1,0} parameter(0)
  %pr.1 = pred[] parameter(1)
  %d.1 = f32[4,4]{1,0} dot(%a.1, %a.1), lhs_contracting_dims={1}, rhs_contracting_dims={0}
  %e.1 = f32[4,4]{1,0} negate(%a.1)
  ROOT %c.1 = f32[4,4]{1,0} conditional(%pr.1, %d.1, %e.1), branch_computations={%b0.1, %b1.1}
}
"""


def test_conditional_branch_parameter_maps_to_branch_operand():
    """Regression (ADVICE r5): a conditional's operand 0 is the predicate;
    branch b's parameter(0) is call-site operand b+1. The old mapping sent
    parameter(0) to operand 0, so a collective-permute inside a branch
    whose argument derives from a dot was falsely certified
    compute-independent — an under-approximation, the one direction the
    module's soundness contract forbids."""
    m = parse_hlo(_COND_ARGS)
    sl0 = backward_slice(m, "b0.1", "cp.1")
    # branch 0's argument is %d.1 (the dot) — the permute DOES depend on it
    assert ("main.1", "d.1") in sl0
    # ...and the mapping is precise: branch 1's argument is not dragged in
    assert ("main.1", "e.1") not in sl0
    # the predicate is a scheduling edge for everything inside a branch
    # (the branch cannot issue before the branch index is known)
    assert ("main.1", "pr.1") in sl0
    # branch 1 symmetrically sees only its own argument
    sl1 = backward_slice(m, "b1.1", "neg.1")
    assert ("main.1", "e.1") in sl1
    assert ("main.1", "d.1") not in sl1


def test_multi_computation_calls_share_one_callee():
    """Two call sites into the same computation: a parameter must continue
    at BOTH call sites (the conservative over-approximation documented in
    the module docstring)."""
    mod = """\
HloModule twocalls, entry_computation_layout={(f32[4]{0}, f32[4]{0})->f32[4]{0}}

%inner.1 (p.1: f32[4]) -> f32[4] {
  %p.1 = f32[4]{0} parameter(0)
  ROOT %d.1 = f32[4]{0} multiply(%p.1, %p.1)
}

ENTRY %main.1 (a.1: f32[4], b.1: f32[4]) -> f32[4] {
  %a.1 = f32[4]{0} parameter(0)
  %b.1 = f32[4]{0} parameter(1)
  %c1.1 = f32[4]{0} call(%a.1), to_apply=%inner.1
  %c2.1 = f32[4]{0} call(%b.1), to_apply=%inner.1
  ROOT %r.1 = f32[4]{0} add(%c1.1, %c2.1)
}
"""
    m = parse_hlo(mod)
    # slicing from inside the callee reaches both callers' operands
    sl = backward_slice(m, "inner.1", "d.1")
    names = {n for _, n in sl}
    assert {"a.1", "b.1"} <= names
