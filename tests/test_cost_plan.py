"""Static cost certification (ISSUE 16): the R8-cost rule, the cost
ledger, and the ledger-driven capacity planner.

Four layers, mirroring test_memory_lint's structure for R7:

- the COST MODEL units: the closed-form FLOP schemes, the roofline's
  binding-leg naming, and the wire-priced collective census on
  hand-written HLO;
- INJECTED counterexamples through the production rule path
  (``engine.run_rules`` — the test_hlo_lint convention): a doctored
  declaration whose closed form cannot name the HLO's work (both
  directions of the exactness breach), an unpriced collective
  (``ragged-all-to-all`` — the spelling that evades the family
  prefixes), and a cell with no declared cost facts at all;
- the LEDGER: exactness on every committed cell (``mxu_flops ==
  analytical_flops``, no tolerance) and drift in both directions
  through the production ``mpi-knn lint --cost --ledger-check`` CLI;
- the PLANNER: in-matrix predictions equal the committed R7 ledger
  byte-for-byte (shared code path, not a parallel model), the matrix
  constants pin lowering's, refusals exit 2 naming the binding
  constraint, and predicted q/s ordering agrees with the committed
  CPU baseline within the nprobe family.
"""

import json

import pytest

from mpi_knn_tpu import plan as plan_mod
from mpi_knn_tpu.analysis import cost, engine, lowering, memory
from mpi_knn_tpu.analysis import rules as rules_mod
from mpi_knn_tpu.config import KNNConfig


def _rules(*names):
    return [r for r in rules_mod.RULES if r.name in names]


def _ctx(target, cfg, meta):
    return engine.LintContext(target=target, cfg=cfg, meta=dict(meta))


# ---------------------------------------------------------------------------
# the cost model units


def test_analytical_schemes_closed_form():
    """Hand-computed counts for every scheme; an unknown scheme is a
    loud error, not a silent zero."""
    assert cost.analytical_mxu_flops({"scheme": "zero"}) == 0
    dense = {"scheme": "dense", "q": 2, "c": 3, "d": 5,
             "sites": 2, "trips": 3}
    assert cost.analytical_mxu_flops(dense) == 2 * 3 * (2 * 2 * 3 * 5)
    mixed = dict(dense, rblocks=2, w=7)
    assert cost.analytical_mxu_flops(mixed) == 2 * 3 * (
        2 * 2 * 3 * 5 + 2 * 2 * 2 * 7 * 5
    )
    ivf = {"scheme": "ivf", "q": 2, "d": 5, "partitions": 4,
           "nprobe": 2, "bucket_cap": 3}
    assert cost.analytical_mxu_flops(ivf) == (
        2 * 2 * 4 * 5 + 2 * 2 * (2 * 3) * 5
    )
    with pytest.raises(ValueError, match="unknown cost scheme"):
        cost.analytical_mxu_flops({"scheme": "mystery", "q": 1, "d": 1})


def test_roofline_names_the_binding_leg():
    prof = {"peak_flops": 100.0, "hbm_bw": 10.0, "ici_bw": 1.0}
    r = cost.roofline(1000, 10, 0, 5, prof)
    assert (r["bound"], r["wall_s"]) == ("mxu", 10.0)
    assert r["qps"] == pytest.approx(0.5)
    # a single wire byte at 1 B/s out-costs everything
    assert cost.roofline(10, 10, 50, 5, prof)["bound"] == "ici"
    assert cost.roofline(10, 1000, 0, 5, prof)["bound"] == "hbm"


def test_profiles_ship_and_unknown_is_loud():
    for name in ("cpu-test", "tpu-v4", "tpu-v5e"):
        p = cost.get_profile(name)
        assert p["peak_flops"] > 0 and p["hbm_bytes"] > 0, name
    with pytest.raises(KeyError, match="cpu-test"):
        cost.get_profile("tpu-v9000")
    assert cost.profile_for_platform("cpu", "cpu") == "cpu-test"
    assert cost.profile_for_platform("tpu", "TPU v4") == "tpu-v4"
    assert cost.profile_for_platform("tpu", "TPU v5 lite") == "tpu-v5e"


_RAGGED = """\
HloModule m, entry_computation_layout={(f32[8,4]{1,0})->f32[8,4]{1,0}}

ENTRY %main.1 (a.1: f32[8,4]) -> f32[8,4] {
  %a.1 = f32[8,4]{1,0} parameter(0)
  ROOT %r.1 = f32[8,4]{1,0} ragged-all-to-all(%a.1), replica_groups={{0,1}}
}
"""

_PRICED = """\
HloModule m, entry_computation_layout={(f32[8,4]{1,0})->f32[8,4]{1,0}}

ENTRY %main.1 (a.1: f32[8,4]) -> f32[8,4] {
  %a.1 = f32[8,4]{1,0} parameter(0)
  ROOT %r.1 = f32[8,4]{1,0} collective-permute(%a.1), source_target_pairs={{0,1},{1,0}}
}
"""


def test_collective_census_prices_and_refuses():
    """A priced collective contributes its result bytes; a family
    opcode outside the registry is a problem, never a silent zero."""
    from mpi_knn_tpu.utils.hlo_graph import parse_hlo

    bytes_, problems = cost.collective_census(parse_hlo(_PRICED))
    assert bytes_ == 8 * 4 * 4 and not problems
    bytes_, problems = cost.collective_census(parse_hlo(_RAGGED))
    assert bytes_ == 0
    assert any("unpriced collective" in p for p in problems), problems


# ---------------------------------------------------------------------------
# injected counterexamples through the production rule path


def _lowered_serial():
    target = lowering.LintTarget("serial", "l2", "float32")
    texts, cfg, meta = lowering.lower_target(target)
    return target, texts, cfg, meta


def test_counterexample_doctored_facts_fire_both_directions():
    """The exactness contract through ``engine.run_rules``: shrink the
    declared corpus extent and the HLO does work the closed form cannot
    name; grow it and the closed form prices a dot the program lost.
    The honest declaration is finding-free."""
    target, texts, cfg, meta = _lowered_serial()
    ok, ran = engine.run_rules(texts, _ctx(target, cfg, meta),
                               _rules("R8-cost"))
    assert ran == ["R8-cost"]
    assert not ok, [f.message for f in ok]

    shrunk = dict(meta)
    shrunk["cost"] = {**meta["cost"], "c": meta["cost"]["c"] // 2}
    findings, _ = engine.run_rules(texts, _ctx(target, cfg, shrunk),
                                   _rules("R8-cost"))
    assert any("cannot name" in f.message for f in findings), [
        f.message for f in findings
    ]
    f = next(f for f in findings if "cannot name" in f.message)
    assert f.details["mxu_flops"] > f.details["analytical_flops"]

    grown = dict(meta)
    grown["cost"] = {**meta["cost"], "c": meta["cost"]["c"] * 2}
    findings, _ = engine.run_rules(texts, _ctx(target, cfg, grown),
                                   _rules("R8-cost"))
    assert any("lost a loop or a dot" in f.message for f in findings), [
        f.message for f in findings
    ]


def test_counterexample_unpriced_collective_is_a_finding():
    """``ragged-all-to-all`` through the production rule path: its
    spelling starts with none of the priced family prefixes, so before
    the ``ragged-`` marker it was invisible to the census — now it is
    an R8 finding naming the instruction."""
    target = lowering.LintTarget("serial", "l2", "float32")
    cfg = KNNConfig(k=4, query_tile=8, corpus_tile=16)
    ctx = _ctx(target, cfg, {"cost": {"scheme": "zero", "queries": 8}})
    findings, _ = engine.run_rules({"after_opt": _RAGGED}, ctx,
                                   _rules("R8-cost"))
    unpriced = [f for f in findings if "unpriced collective" in f.message]
    assert unpriced, [f.message for f in findings]
    assert "ragged-all-to-all" in unpriced[0].message
    # the priced spelling of the same program is census-clean
    ctx2 = _ctx(target, cfg, {"cost": {"scheme": "zero", "queries": 8}})
    ok, _ = engine.run_rules({"after_opt": _PRICED}, ctx2,
                             _rules("R8-cost"))
    assert not ok, [f.message for f in ok]


def test_counterexample_missing_cost_facts_is_a_finding():
    """A cell that declares no ``meta['cost']`` cannot be certified —
    that absence is itself a finding, not a skipped check."""
    target, texts, cfg, meta = _lowered_serial()
    bare = {k: v for k, v in meta.items() if k != "cost"}
    findings, _ = engine.run_rules(texts, _ctx(target, cfg, bare),
                                   _rules("R8-cost"))
    assert any("declares no cost facts" in f.message for f in findings)


# ---------------------------------------------------------------------------
# the committed ledger + drift through the production CLI


def test_committed_cost_ledger_is_exact_on_every_cell():
    """The committed artifact covers the full matrix and holds the
    exactness contract with NO tolerance: the HLO counter and the
    closed form agree to the FLOP on every cell, and every roofline
    names its binding resource."""
    doc = cost.load_cost_ledger(cost.DEFAULT_COST_LEDGER)
    assert doc is not None, "artifacts/lint/cost_ledger.json missing"
    assert len(doc["cells"]) >= 70
    for label, cell in doc["cells"].items():
        assert cell["mxu_flops"] == cell["analytical_flops"], label
        assert cell["roofline"]["bound"] in ("mxu", "hbm", "ici"), label
        assert cell["queries"] > 0, label
        if cell["mxu_flops"]:
            assert cell["largest_dot"]["instruction"], label


def test_cost_ledger_drift_through_production_cli(tmp_path):
    """Drift in BOTH directions through the real ``mpi-knn lint --cost
    --ledger-check`` path: a committed ledger claiming half the real
    FLOPs (the program grew) and one claiming double (the ledger went
    stale) must both fail the gate; the honest ledger passes."""
    from mpi_knn_tpu.analysis import cli as lint_cli

    args = ["--backend", "serial", "--metric", "l2", "--dtype",
            "float32", "--policy", "exact", "--schedule", "uni",
            "--out", str(tmp_path), "-q"]
    assert lint_cli.main(args + ["--cost"]) == 0
    ledger_path = tmp_path / "cost_ledger.json"
    honest = json.loads(ledger_path.read_text())
    label = "serial/l2/float32"
    assert label in honest["cells"]
    assert lint_cli.main(args + ["--cost", "--ledger-check"]) == 0
    # the program "grew" past the committed claim
    tampered = json.loads(json.dumps(honest))
    tampered["cells"][label]["mxu_flops"] //= 2
    ledger_path.write_text(json.dumps(tampered))
    assert lint_cli.main(args + ["--cost", "--ledger-check"]) == 1
    # the committed claim went stale above the real program
    tampered = json.loads(json.dumps(honest))
    tampered["cells"][label]["mxu_flops"] *= 2
    ledger_path.write_text(json.dumps(tampered))
    assert lint_cli.main(args + ["--cost", "--ledger-check"]) == 1
    # usage errors stay loud: --ledger-check without a ledger flag, a
    # --rule filter that would sweep WITHOUT R8, a missing committed
    # ledger
    assert lint_cli.main(args + ["--ledger-check"]) == 2
    assert lint_cli.main(args + ["--cost", "--rule", "R2-memory"]) == 2
    assert lint_cli.main(
        args + ["--cost", "--ledger-check",
                "--cost-ledger", str(tmp_path / "nope.json")]
    ) == 2


# ---------------------------------------------------------------------------
# the planner: shared code path with R7/R8, not a parallel model


def test_plan_matrix_constants_pin_lowering():
    """The planner's in-matrix shapes ARE lowering's lint shapes — a
    drift here silently downgrades byte-exact ledger lookups to model
    estimates."""
    assert plan_mod.MATRIX_DENSE == {
        "m": lowering.LINT_M, "d": lowering.LINT_D,
        "k": lowering.LINT_K, "bucket": lowering.LINT_NQ,
    }
    assert plan_mod.MATRIX_IVF == {
        "m": lowering.LINT_M_IVF, "d": lowering.LINT_D,
        "k": lowering.LINT_K, "bucket": lowering.LINT_NQ,
        "partitions": lowering.LINT_PARTITIONS,
        "nprobe": lowering.LINT_NPROBE,
        "shards": lowering.LINT_IVF_SHARDS,
    }


def test_plan_in_matrix_peak_equals_r7_ledger_byte_for_byte():
    committed = memory.load_ledger(plan_mod.DEFAULT_PLAN_LEDGER)
    assert committed is not None
    ref = plan_mod.MATRIX_IVF
    wl_dense = plan_mod.Workload(m=128, d=32, k=4, bucket=64)
    wl_ivf = plan_mod.Workload(m=ref["m"], d=32, k=4, bucket=64)
    cases = [
        (plan_mod.Candidate("serial"), wl_dense,
         "serial/l2/float32/serve"),
        (plan_mod.Candidate("ivf", partitions=ref["partitions"],
                            nprobe=ref["nprobe"]), wl_ivf,
         "ivf/l2/float32/serve"),
        (plan_mod.Candidate("ivf-sharded", partitions=ref["partitions"],
                            nprobe=ref["nprobe"],
                            shards=ref["shards"]), wl_ivf,
         "ivf-sharded/l2/float32/serve"),
    ]
    for cand, wl, label in cases:
        got = plan_mod.predict_peak_hbm(cand, wl)
        assert got["source"] == f"ledger:{label}", got
        assert got["peak_hbm_bytes"] == (
            committed["cells"][label]["peak_bytes"]
        ), label
    # and through the full search: the dense lint workload plans onto
    # the committed serial serve cell
    doc = plan_mod.plan(
        plan_mod.Workload(m=128, d=32, k=4, bucket=64,
                          recall_target=0.9),
        plan_mod.Fleet(), backends=("serial",), dtypes=("float32",),
    )
    assert doc["predicted"]["peak_hbm_source"] == (
        "ledger:serial/l2/float32/serve"
    )
    assert doc["predicted"]["peak_hbm_bytes"] == (
        committed["cells"]["serial/l2/float32/serve"]["peak_bytes"]
    )


def test_plan_off_matrix_uses_the_model_and_r7_decomposition():
    cand = plan_mod.Candidate("ivf", partitions=64, nprobe=4)
    wl = plan_mod.Workload(m=4096, d=64, k=10, bucket=128)
    got = plan_mod.predict_peak_hbm(cand, wl)
    assert got["source"] == "model"
    # the model is R7's own budget decomposition: args + outputs + the
    # temp allowance from analysis.memory — strictly more than the
    # resident store alone
    assert got["peak_hbm_bytes"] > 4096 * 64 * 4 / 64 * 4


@pytest.mark.parametrize(
    "argv,constraint,needle",
    [
        (["--corpus", "100000000", "--dim", "128",
          "--hbm-bytes", "1000000"], "hbm", "exceeds the budget"),
        (["--corpus", "4096", "--dim", "32", "--recall-target",
          "0.999", "--dtype", "int4"], "recall", "int4"),
        (["--corpus", "4096", "--dim", "32",
          "--qps", "1000000000000"], "qps", "roofline"),
    ],
)
def test_plan_refusals_exit_2_naming_the_binding_constraint(
    capsys, argv, constraint, needle
):
    rc = plan_mod.main(argv + ["-q"])
    assert rc == 2
    doc = json.loads(capsys.readouterr().out)
    assert doc["feasible"] is False
    assert doc["binding_constraint"] == constraint
    assert needle in doc["detail"]
    assert doc["rejected"][constraint] > 0
    assert doc["closest_candidate"]["backend"] in plan_mod.PLAN_BACKENDS


def test_plan_feasible_cli_emits_runnable_commands(capsys):
    rc = plan_mod.main(["--corpus", "2048", "--dim", "32", "--bucket",
                        "128", "--recall-target", "0.9", "-q"])
    assert rc == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["feasible"] is True
    assert doc["predicted"]["recall_at_k"] >= 0.9
    assert doc["commands"]["serve"].startswith("mpi-knn ")
    assert doc["predicted"]["roofline_bound"] in ("mxu", "hbm", "ici")
    # the unknown-profile refusal is a usage error, not a traceback
    assert plan_mod.main(["--corpus", "64", "--dim", "8",
                          "--device-profile", "tpu-v9000", "-q"]) == 2


def test_recall_calibration_is_monotone_and_dtype_capped():
    calib = plan_mod.load_calibration()
    fracs = [f for f, _ in calib["points"]]
    assert fracs == sorted(fracs) and len(fracs) >= 3
    rec = [plan_mod.predict_recall(f, "float32", calib)
           for f in fracs + [1.0]]
    assert rec == sorted(rec), rec
    scale = calib["dtype_scale"]
    assert scale["float32"] == pytest.approx(1.0)
    assert scale["int4"] < scale["int8"] <= 1.0
    # the int4 ceiling is the measured quantization cap — the number a
    # recall refusal names
    assert plan_mod.predict_recall(1.0, "int4", calib) < 0.95


def test_predicted_qps_ordering_matches_cpu_baseline_family():
    """Within the committed ivf_query nprobe family the measured q/s
    is strictly decreasing in nprobe — the planner's roofline must
    order the same configs the same way (ordering, not magnitude: the
    cpu-test profile is a declared stand-in, not a measured machine)."""
    doc = json.loads(
        (plan_mod.DEFAULT_BENCH).read_text()
    )
    family = {
        r["variant"]: r for r in doc["results"]
        if r.get("op") == "ivf_query"
    }
    measured = [family[f"p64-nprobe{n}"]["queries_per_s"]
                for n in (1, 4, 16)]
    assert measured == sorted(measured, reverse=True), measured
    prof = cost.get_profile("cpu-test")
    wl = plan_mod.Workload(m=61440, d=64, k=10, bucket=64)
    predicted = [
        plan_mod.predict_qps(
            plan_mod.Candidate("ivf", partitions=64, nprobe=n), wl, prof
        )["qps"]
        for n in (1, 4, 16)
    ]
    assert predicted == sorted(predicted, reverse=True), predicted


def test_bench_baseline_carries_roofline_columns():
    """Every serving row of the committed CPU baseline names its
    roofline cell and carries the prediction from the committed cost
    ledger — the static number the measured one is compared against."""
    doc = json.loads(plan_mod.DEFAULT_BENCH.read_text())
    ledger = cost.load_cost_ledger(cost.DEFAULT_COST_LEDGER)
    seen = 0
    for r in doc["results"]:
        if "roofline_cell" not in r:
            continue
        seen += 1
        assert r["roofline_cell"] == r["peak_hbm_cell"]
        cell = ledger["cells"][r["roofline_cell"]]
        assert r["predicted_qps"] == round(cell["roofline"]["qps"], 1)
        # static roofline is an upper bound; the host CPU baseline
        # must not beat physics
        assert r["queries_per_s"] <= r["predicted_qps"]
    assert seen >= 3
